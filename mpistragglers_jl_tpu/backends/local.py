"""Thread-pool worker backend: the fast, fake-able transport.

The reference has no in-process backend at all — its only execution mode is
``mpiexec`` spawning real OS processes, and its test harness shells out to
``mpiexec -n N julia`` per scenario (test/runtests.jl:17), which SURVEY §4
calls out as the weakness to fix. :class:`LocalBackend` is that fix: the
worker loop of examples/iterative_example.jl:55-82 (receive -> compute ->
send, with a control channel for shutdown) becomes a first-class library
API, with *deterministic* straggler injection replacing the reference's
``sleep(rand())`` (examples/iterative_example.jl:74, test/kmap2.jl:95).

Each worker is a daemon thread with a depth-1 mailbox (a dispatched payload
waits there while the worker is busy, exactly like an ``MPI.Isend`` whose
matching ``Irecv!`` the worker only posts after finishing its previous
compute — reference §3.2 call stack). ``shutdown()`` posts a sentinel on
the mailbox, the analog of the reference's control-tag broadcast
(test/kmap2.jl:14-18).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

import numpy as np

from .base import SlotBackend, WorkerError

WorkFn = Callable[[int, np.ndarray, int], object]
DelayFn = Callable[[int, int], float]

_SHUTDOWN = object()


class LocalBackend(SlotBackend):
    """n worker threads computing ``work_fn(worker_index, payload, epoch)``.

    Parameters
    ----------
    work_fn:
        The worker computation. Receives the pool-local worker index, a
        private snapshot of the dispatched payload, and the epoch it was
        dispatched at (so workloads can echo it, as the reference tests
        make workers do — test/kmap2.jl:92-94).
    n_workers:
        Pool size.
    delay_fn:
        Optional deterministic latency injection: seconds to stall before
        computing, as a function of ``(worker_index, epoch)``. First-class
        replacement for the reference's random sleeps (SURVEY §7 "the hard
        parts": injection must be deterministic and first-class).
    """

    def __init__(
        self,
        work_fn: WorkFn,
        n_workers: int,
        *,
        delay_fn: DelayFn | None = None,
    ):
        super().__init__(n_workers)
        self.work_fn = work_fn
        self.delay_fn = delay_fn
        self._closed = False
        self._mailboxes: list[queue.Queue] = [
            queue.Queue(maxsize=1) for _ in range(n_workers)
        ]
        self._threads = [
            threading.Thread(
                target=self._worker_loop, args=(i,), daemon=True,
                name=f"pool-worker-{i}",
            )
            for i in range(n_workers)
        ]
        for t in self._threads:
            t.start()

    def _worker_loop(self, i: int) -> None:
        """The reference's worker_main convention, as library code.

        Loop: take next payload (blocks like the worker-side
        ``MPI.Waitany!([control, data])`` select, reference §3.2),
        optionally stall (injected straggling), compute, deliver. A
        shutdown sentinel breaks the loop — the control channel.
        """
        mbox = self._mailboxes[i]
        while True:
            msg = mbox.get()
            if msg is _SHUTDOWN:
                return
            seq, payload, epoch = msg
            if self.delay_fn is not None:
                d = float(self.delay_fn(i, epoch))
                if d > 0:
                    time.sleep(d)
            try:
                result = self.work_fn(i, payload, epoch)
            except BaseException as e:  # surfaced on harvest, not lost
                result = WorkerError(i, epoch, e)
            self._complete(i, seq, result)

    def _start(self, i: int, sendbuf, epoch: int, seq: int, tag: int) -> None:
        if self._closed:
            raise RuntimeError("backend has been shut down")
        # Snapshot at dispatch time: the reference's per-worker isendbuf
        # copy (src/MPIAsyncPools.jl:130) — in-flight sends must survive
        # caller mutation of sendbuf.
        payload = np.array(sendbuf, copy=True)
        self._mailboxes[i].put((seq, payload, epoch))

    def shutdown(self) -> None:
        self._closed = True
        for mbox in self._mailboxes:
            try:
                mbox.put_nowait(_SHUTDOWN)
            except queue.Full:
                pass  # worker busy with a task it will never deliver; daemon
        for t in self._threads:
            t.join(timeout=1.0)
