"""Thread-pool worker backend: the fast, fake-able transport.

The reference has no in-process backend at all — its only execution mode is
``mpiexec`` spawning real OS processes, and its test harness shells out to
``mpiexec -n N julia`` per scenario (test/runtests.jl:17), which SURVEY §4
calls out as the weakness to fix. :class:`LocalBackend` is that fix: pure
numpy worker threads with *deterministic* straggler injection replacing the
reference's ``sleep(rand())`` (examples/iterative_example.jl:74,
test/kmap2.jl:95). The worker loop itself lives in
:class:`~.base.MailboxBackend`.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from .base import MailboxBackend, DelayFn

WorkFn = Callable[[int, np.ndarray, int], object]


class LocalBackend(MailboxBackend):
    """n worker threads computing ``work_fn(worker_index, payload, epoch)``.

    Parameters
    ----------
    work_fn:
        The worker computation. Receives the pool-local worker index, a
        private snapshot of the dispatched payload, and the epoch it was
        dispatched at (so workloads can echo it, as the reference tests
        make workers do — test/kmap2.jl:92-94).
    n_workers:
        Pool size.
    delay_fn:
        Deterministic latency injection: seconds to stall before
        computing, as a function of ``(worker_index, epoch)``.
    """

    def __init__(
        self,
        work_fn: WorkFn,
        n_workers: int,
        *,
        delay_fn: DelayFn | None = None,
    ):
        self.work_fn = work_fn
        super().__init__(
            n_workers, delay_fn=delay_fn, join_timeout=1.0,
            thread_name="local-worker",
        )

    def _snapshot(self, i: int, sendbuf, epoch: int) -> np.ndarray:
        # host copy: the reference's per-worker isendbuf discipline
        # (src/MPIAsyncPools.jl:130) — in-flight sends survive caller
        # mutation of sendbuf
        return np.array(sendbuf, copy=True)

    def _compute(self, i: int, payload: np.ndarray, epoch: int):
        return self.work_fn(i, payload, epoch)
