"""OS-process worker backend: real process isolation, serialized transport.

The reference's only execution mode is ``mpiexec`` spawning one OS process
per rank, with every payload crossing a real process boundary
(test/runtests.jl:17; workers speak raw ``MPI.Irecv!``/``Isend`` —
examples/iterative_example.jl:55-82). :class:`LocalBackend` deliberately
replaces that with threads for fast unit tests; :class:`ProcessBackend` is
the faithful counterpart: n spawned worker *processes*, payloads pickled
over OS pipes (serialization is the in-host analog of the network hop),
a per-worker shutdown sentinel standing in for the reference's
control-tag broadcast (test/kmap2.jl:14-18), and — beyond the reference —
dead-worker detection: a worker process dying mid-task surfaces as a
:class:`~.base.WorkerFailure` at harvest instead of hanging the pool the
way a dead rank hangs ``MPI.Waitall!`` (SURVEY §5 'Failure detection').

Because workers are spawned processes, ``work_fn`` and ``delay_fn`` must
be picklable: module-level functions, ``functools.partial`` of them, or
instances of module-level classes defining ``__call__`` (the fault
schedules in :mod:`..utils.faults` qualify).
"""

from __future__ import annotations

import multiprocessing as mp
import threading
import time
import traceback
from typing import Callable

import numpy as np

from .base import DelayFn, SlotBackend, WorkerError

WorkFn = Callable[[int, object, int], object]

__all__ = ["ProcessBackend", "RemoteWorkerError", "WorkerProcessDied"]


class RemoteWorkerError(RuntimeError):
    """A worker process raised during compute; carries the remote traceback
    (the reference loses these entirely — assertions die inside mpiexec
    subprocesses and only garble stdout, SURVEY §4)."""

    def __init__(self, exc_type: str, message: str, remote_traceback: str):
        self.exc_type = exc_type
        self.remote_traceback = remote_traceback
        super().__init__(f"{exc_type}: {message}\n{remote_traceback}")


class WorkerProcessDied(RuntimeError):
    """The worker OS process exited without delivering its result."""

    def __init__(self, worker: int):
        self.worker = worker
        super().__init__(f"worker process {worker} died")


def _worker_main(
    i: int, conn, work_fn: WorkFn, delay_fn: DelayFn | None,
    telemetry: bool = False,
) -> None:
    """Worker process entry: the reference's receive -> stall -> compute ->
    send loop (§3.2) over a pipe instead of MPI point-to-point.

    ``telemetry=True`` (set when the coordinator was constructed with a
    ``registry``) keeps a worker-local
    :class:`~..obs.aggregate.WorkerTelemetry` whose snapshot rides each
    result tuple as a 6th element — tasks/errors counters, compute-wall
    histogram, per-task spans, and the worker-clock stamps the
    coordinator's clock aligner pairs with its own. One final frame is
    sent on the shutdown drain so end-of-run telemetry is not lost."""
    tele = None
    if telemetry:
        from ..obs.aggregate import WorkerTelemetry

        tele = WorkerTelemetry(i)
    try:
        while True:
            msg = conn.recv()
            t_recv_w = time.perf_counter() if tele is not None else 0.0
            if msg is None:  # shutdown sentinel (control channel)
                if tele is not None:
                    # drain frame: the last inter-result telemetry
                    conn.send((-1, -1, "tele", tele.snapshot(), -1))
                break
            seq, payload, epoch, tag = msg
            stall = 0.0
            if delay_fn is not None:
                d = float(delay_fn(i, epoch))
                if d > 0:
                    stall = d
                    time.sleep(d)
            t0 = time.perf_counter() if tele is not None else 0.0
            try:
                out = (seq, epoch, "ok", work_fn(i, payload, epoch), tag)
                failed = False
            except BaseException as e:
                out = (
                    seq, epoch, "error",
                    (type(e).__name__, str(e), traceback.format_exc()),
                    tag,
                )
                failed = True
            frame = None
            if tele is not None:
                t1 = time.perf_counter()
                tele.task_done(epoch, t0, t1, error=failed, stall=stall)
                # t_send_w stamped by snapshot construction time — the
                # tiny build cost lands in the transport-delay half,
                # where the min-delay offset filter absorbs it
                frame = tele.snapshot(pair=(seq, t_recv_w, t1))
                out = out + (frame,)
            try:
                conn.send(out)
            except Exception as e:  # result not picklable
                err = (
                    seq, epoch, "error",
                    (type(e).__name__,
                     f"worker result could not be serialized: {e}", ""),
                    tag,
                )
                try:
                    # snapshot() drained the spans destructively;
                    # reattach the SAME frame so the failing task's
                    # span and clock pair survive — the postmortem
                    # case needs them most
                    conn.send(err if frame is None else err + (frame,))
                except Exception:
                    # the frame itself held the unpicklable value (a
                    # custom span arg): the error result must still
                    # reach the coordinator, not kill the worker
                    conn.send(err)
    except (EOFError, OSError, KeyboardInterrupt):
        pass
    finally:
        conn.close()


class ProcessBackend(SlotBackend):
    """n spawned worker processes computing ``work_fn(i, payload, epoch)``.

    The payload snapshot the reference takes via ``isendbufs[i] .= sendbuf``
    (src/MPIAsyncPools.jl:130) happens here by construction: pickling at
    dispatch time copies the payload, so in-flight sends survive caller
    mutation. numpy arrays cross the pipe zero-conversion; jax arrays are
    converted to numpy at dispatch (device buffers are not picklable).

    Parameters
    ----------
    work_fn:
        Picklable worker computation ``(worker_index, payload, epoch) ->
        result``.
    n_workers:
        Pool size (= number of spawned processes).
    delay_fn:
        Picklable deterministic latency injection, seconds as a function
        of ``(worker_index, epoch)``, applied *inside* the worker process.
    mp_context:
        ``multiprocessing`` start method; ``"spawn"`` (default) is safe
        with JAX/threads in the coordinator, ``"fork"`` is faster to boot
        for pure-numpy workers.
    registry:
        Opt-in cross-process telemetry (the obs/ contract — None = dark,
        zero cost): worker processes keep a local registry whose
        snapshots piggyback on result frames and merge here under a
        ``worker="<rank>"`` label with counter-delta semantics across
        respawns; worker spans land clock-aligned in
        ``self.aggregator.recorders()`` (one Perfetto pid per worker
        process — :mod:`..obs.aggregate`).
    flight:
        Optional :class:`~..obs.FlightRecorder`: merged worker spans are
        mirrored into the ring so a hang postmortem shows what every
        worker process was doing last.
    exporter:
        Optional :class:`~..obs.ObsServer`: registers the pool's
        worker-deadness health check (``/healthz`` flips when a worker
        dies, recovers after :meth:`respawn`) and the aggregator's
        per-worker trace sources.
    """

    def __init__(
        self,
        work_fn: WorkFn,
        n_workers: int,
        *,
        delay_fn: DelayFn | None = None,
        mp_context: str = "spawn",
        join_timeout: float = 5.0,
        registry=None,
        flight=None,
        exporter=None,
    ):
        super().__init__(n_workers)
        self.work_fn = work_fn
        self.delay_fn = delay_fn
        self._join_timeout = join_timeout
        self._closed = False
        self._dead = [False] * self.n_workers
        self._send_lock = threading.Lock()
        self._mp_context = mp_context
        ctx = mp.get_context(mp_context)
        self.aggregator = None
        if registry is not None or flight is not None:
            from ..obs.aggregate import TelemetryAggregator

            self.aggregator = TelemetryAggregator(
                registry, flight=flight
            )
        self._conns = [None] * self.n_workers
        self._procs = [None] * self.n_workers
        self._readers = [None] * self.n_workers
        for i in range(self.n_workers):
            self._spawn_worker(i)
        if exporter is not None:
            exporter.register_backend(self)

    def _spawn_worker(self, i: int) -> None:
        """Start (or restart) worker process i and its reader thread."""
        ctx = mp.get_context(self._mp_context)
        parent, child = ctx.Pipe(duplex=True)
        proc = ctx.Process(
            target=_worker_main,
            args=(i, child, self.work_fn, self.delay_fn,
                  self.aggregator is not None),
            daemon=True,
            name=f"pool-proc-worker-{i}",
        )
        proc.start()
        child.close()  # parent keeps only its end; EOF works
        self._conns[i] = parent
        self._procs[i] = proc
        # _dead is written from reader threads too (_on_worker_death);
        # all its writers take the completion lock (GC005)
        with self._cond:
            self._dead[i] = False
        reader = threading.Thread(
            target=self._reader_loop, args=(i,), daemon=True,
            name=f"pool-proc-reader-{i}",
        )
        self._readers[i] = reader
        reader.start()

    def respawn(self, i: int) -> None:
        """Elastic recovery: replace a dead worker process with a fresh
        one on the same rank (the reference has no such capability — a
        dead rank is permanent and hangs ``Waitall!``, SURVEY §5). The
        rank becomes dispatchable again; the old reader thread has
        already exited on its pipe's EOF."""
        if self._closed:
            raise RuntimeError("backend has been shut down")
        if not self._dead[i] and self._procs[i].is_alive():
            raise RuntimeError(f"worker {i} is alive; nothing to respawn")
        if self._procs[i].is_alive():  # pragma: no cover - wedged worker
            self._procs[i].terminate()
        self._procs[i].join(timeout=self._join_timeout)
        old_reader = self._readers[i]
        self._conns[i].close()  # unblock the old reader if still parked
        if old_reader is not None:
            old_reader.join(timeout=self._join_timeout)
        self._spawn_worker(i)

    # -- coordinator-side completion pump ---------------------------------
    def _reader_loop(self, i: int) -> None:
        conn = self._conns[i]
        agg = self.aggregator
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                self._on_worker_death(i, conn)
                return
            if msg is None:
                return
            t_recv_c = (
                time.perf_counter() if agg is not None else None
            )
            seq, epoch, kind, payload, tag, *tele = msg
            if kind == "tele":  # shutdown-drain telemetry frame
                if agg is not None:
                    agg.merge(i, payload)
                continue
            if agg is not None and tele:
                # merge BEFORE completing: a scrape racing the harvest
                # sees the worker series of every result the pool has
                agg.merge(i, tele[0], t_recv_c=t_recv_c)
            if kind == "error":
                exc_type, message, tb = payload
                payload = WorkerError(
                    i, epoch, RemoteWorkerError(exc_type, message, tb)
                )
            self._complete(i, seq, payload, tag)

    def _on_worker_death(self, i: int, conn) -> None:
        """Fail the outstanding task (if any) so waits don't hang — the
        capability the reference lacks (dead rank hangs ``Waitall!``)."""
        if self._conns[i] is not conn:
            return  # stale EOF from a pre-respawn incarnation
        # fail the outstanding task on EVERY tag channel: the process is
        # gone, so no channel's completion can ever arrive. The _dead
        # stamp shares the same lock acquisition — this runs on the
        # reader thread while _start/_spawn_worker write the flag from
        # the coordinator (GC005 lock discipline)
        with self._cond:
            self._dead[i] = True
            pending = [
                (tag, slots[i].seq)
                for tag, slots in self._channels.items()
                if slots[i].outstanding and not slots[i].done
            ]
        if not self._closed:
            for tag, seq in pending:
                self._complete(
                    i, seq, WorkerError(i, -1, WorkerProcessDied(i)), tag
                )

    def dead_workers(self) -> list[int]:
        """Ranks whose worker process is currently dead (not yet
        respawned) — the ``/healthz`` pool check reads this."""
        with self._cond:
            return [i for i, d in enumerate(self._dead) if d]

    # -- SlotBackend surface ----------------------------------------------
    def _start(self, i: int, sendbuf, epoch: int, seq: int, tag: int) -> None:
        if self._closed:
            raise RuntimeError("backend has been shut down")
        if self._dead[i]:  # fail fast instead of writing to a broken pipe
            self._complete(
                i, seq, WorkerError(i, epoch, WorkerProcessDied(i)), tag
            )
            return
        payload = sendbuf
        if hasattr(payload, "__array__") and not isinstance(payload, np.ndarray):
            payload = np.asarray(payload)  # device arrays are not picklable
        if self.aggregator is not None:
            # half of a clock-offset sample; the worker's matching
            # stamps ride back on the result frame
            self.aggregator.note_dispatch(i, seq, time.perf_counter())
        try:
            with self._send_lock:
                self._conns[i].send((seq, payload, epoch, tag))
        except (BrokenPipeError, OSError):
            with self._cond:  # racing _on_worker_death's stamp (GC005)
                self._dead[i] = True
            self._complete(
                i, seq, WorkerError(i, epoch, WorkerProcessDied(i)), tag
            )

    def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        for i, conn in enumerate(self._conns):
            try:
                with self._send_lock:
                    conn.send(None)
            except (BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=self._join_timeout)
        for proc in self._procs:
            if proc.is_alive():  # pragma: no cover - stuck worker
                proc.terminate()
                proc.join(timeout=self._join_timeout)  # reap before close
        for proc in self._procs:
            if not proc.is_alive():
                proc.close()  # release the spawn sentinel fds deterministically
        if self.aggregator is not None:
            # the reader threads are the ones merging the workers'
            # shutdown-drain telemetry frames; the workers have exited
            # (pipes at EOF), so the readers finish promptly — join
            # them BEFORE closing the conns, or the final deltas race
            # the close and are lost nondeterministically (the pipe
            # twin of the native backend's _drain_obs)
            for reader in self._readers:
                if reader is not None:
                    reader.join(timeout=self._join_timeout)
        for conn in self._conns:
            conn.close()
