"""OS-process worker backend: real process isolation, serialized transport.

The reference's only execution mode is ``mpiexec`` spawning one OS process
per rank, with every payload crossing a real process boundary
(test/runtests.jl:17; workers speak raw ``MPI.Irecv!``/``Isend`` —
examples/iterative_example.jl:55-82). :class:`LocalBackend` deliberately
replaces that with threads for fast unit tests; :class:`ProcessBackend` is
the faithful counterpart: n spawned worker *processes*, payloads pickled
over OS pipes (serialization is the in-host analog of the network hop),
a per-worker shutdown sentinel standing in for the reference's
control-tag broadcast (test/kmap2.jl:14-18), and — beyond the reference —
dead-worker detection: a worker process dying mid-task surfaces as a
:class:`~.base.WorkerFailure` at harvest instead of hanging the pool the
way a dead rank hangs ``MPI.Waitall!`` (SURVEY §5 'Failure detection').

Because workers are spawned processes, ``work_fn`` and ``delay_fn`` must
be picklable: module-level functions, ``functools.partial`` of them, or
instances of module-level classes defining ``__call__`` (the fault
schedules in :mod:`..utils.faults` qualify).
"""

from __future__ import annotations

import multiprocessing as mp
import pickle
import threading
import time
import traceback
import weakref
from typing import Callable

import numpy as np

from ..native import rings as _rings
from .base import DelayFn, SlotBackend, WorkerError

WorkFn = Callable[[int, object, int], object]

__all__ = ["ProcessBackend", "RemoteWorkerError", "WorkerProcessDied"]

# Round-12 zero-copy pipe transport: ndarray payloads of at least this
# many bytes ride ``multiprocessing.shared_memory`` rings (pickle
# protocol-5 out-of-band buffers), the pipes carrying only small
# control frames. Below it, classic in-band pickling wins.
PROC_RING_MIN = 1 << 16
PROC_RING_SLOTS = 4

# control-frame markers (first tuple element)
_MARK_BCAST = "__shmb__"   # dispatch body in the shared broadcast ring
_MARK_RESULT = "__shmr__"  # result body in the worker's result ring
_MARK_ACK = "__ack__"      # slot-release records, either direction


def _attach_shm(name: str):
    """Attach an existing shared-memory segment READ-ONLY, bypassing
    ``SharedMemory`` on the attach side: attaching via the class
    registers the name with the (spawn-shared) resource tracker a
    second time, which corrupts the creator's unlink accounting
    (bpo-38119) and spews tracker KeyErrors; a plain read-only mmap of
    the POSIX segment has no tracker interaction and gives the
    read-only payload contract for free. Returns ``(mmap, base)`` with
    ``base`` a read-only uint8 array over the whole segment."""
    import mmap as _mmap
    import os as _os

    fd = _os.open(f"/dev/shm/{name}", _os.O_RDONLY)
    try:
        size = _os.fstat(fd).st_size
        mm = _mmap.mmap(fd, size, _mmap.MAP_SHARED, _mmap.PROT_READ)
    finally:
        _os.close(fd)
    return mm, np.frombuffer(mm, np.uint8)


def _unlink_shm_quiet(name: str) -> None:
    """Best-effort unlink of a POSIX shared-memory name (the parent's
    crash-path safety net for worker result rings; the creating worker
    unlinks on clean exit)."""
    import os as _os

    try:
        _os.unlink(f"/dev/shm/{name}")
    except OSError:
        pass


def _encode_oob(obj) -> tuple[bytes, list]:
    """Pickle with protocol-5 out-of-band buffers: ``(data, views)``
    where ``views`` are the raw contiguous buffer views (ndarray
    memory) the unpickler must be handed back in order. Empty views =
    nothing eligible (no arrays, or non-contiguous fallbacks pickled
    in-band)."""
    bufs: list = []
    data = pickle.dumps(obj, protocol=5, buffer_callback=bufs.append)
    return data, [b.raw() for b in bufs]


def _serve_slot_views(base, start: int, lens, on_release, *args):
    """Read-only views over one ring slot's packed buffers, with a
    counted release hook: ``on_release(*args)`` fires once, when the
    LAST derived view dies (the unpickled arrays keep these as their
    bases). ``base`` must be a read-only uint8 array over the whole
    segment."""
    views = []
    pos = start
    for n in lens:
        views.append(base[pos:pos + n])
        pos += n
    state = {"left": len(views)}
    lock = threading.Lock()

    def _dec():
        with lock:
            state["left"] -= 1
            done = state["left"] == 0
        if done:
            on_release(*args)

    for v in views:
        weakref.finalize(v, _dec)
    # hand out MEMORYVIEWS of the tracked slices: np.frombuffer (which
    # is how pickle-5 reconstructs arrays) does not keep an ndarray
    # buffer-source object alive, only its root buffer — the finalizer
    # would fire (and the slot recycle) under live arrays. A
    # memoryview's managed buffer holds the slice strongly and every
    # derived buffer shares it.
    return [memoryview(v) for v in views]


class _ShmRing:
    """One SharedMemory segment divided into equal slots (producer
    side). ``create`` returns None when shared memory is unavailable
    (callers fall back to in-band pickling)."""

    __slots__ = ("shm", "name", "slots", "slot_bytes", "view", "alloc")

    def __init__(self, shm, slots: int):
        self.shm = shm
        self.name = shm.name
        self.slots = int(slots)
        self.slot_bytes = shm.size // self.slots
        self.view = np.frombuffer(shm.buf, np.uint8)
        self.alloc = _rings.RingAlloc(self.slots)

    @classmethod
    def create(cls, body_bytes: int, slots: int):
        from multiprocessing import shared_memory

        size = max(_rings.next_pow2(body_bytes), PROC_RING_MIN) * slots
        try:
            shm = shared_memory.SharedMemory(create=True, size=size)
        except (OSError, ValueError):  # pragma: no cover - /dev/shm full
            return None
        return cls(shm, slots)

    def destroy(self) -> None:
        """Creator-side teardown: drop our view, close, unlink. Safe
        against double-unlink (a hard-killed peer may have beaten us)."""
        self.view = None
        try:
            self.shm.close()
        except BufferError:  # pragma: no cover - lingering local view
            pass
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass


class RemoteWorkerError(RuntimeError):
    """A worker process raised during compute; carries the remote traceback
    (the reference loses these entirely — assertions die inside mpiexec
    subprocesses and only garble stdout, SURVEY §4)."""

    def __init__(self, exc_type: str, message: str, remote_traceback: str):
        self.exc_type = exc_type
        self.remote_traceback = remote_traceback
        super().__init__(f"{exc_type}: {message}\n{remote_traceback}")


class WorkerProcessDied(RuntimeError):
    """The worker OS process exited without delivering its result."""

    def __init__(self, worker: int):
        self.worker = worker
        super().__init__(f"worker process {worker} died")


def _child_resolve_bcast(marker, brings, pending_acks):
    """Reconstruct a dispatch payload from the shared broadcast ring:
    attach the segment on first sight (mapped once, reused every
    epoch), unpickle with the slot's read-only views handed back as
    protocol-5 out-of-band buffers (zero copy), and register the
    slot-release ack that fires when the payload's last view dies."""
    _, name, slot_bytes, slots, slot, gen, lens, data = marker
    entry = brings.get(name)
    if entry is None:
        brings[name] = entry = _attach_shm(name)
        for old in [k for k in brings if k != name]:
            del brings[old]  # superseded ring; GC closes once views die
    views = _serve_slot_views(
        entry[1], slot * slot_bytes, lens,
        pending_acks.append, (name, slot, gen),
    )
    return pickle.loads(data, buffers=views)


def _child_ring_result(rring_box, result):
    """Try to stage ``result``'s array buffers in this worker's result
    ring; returns the control marker, or None for in-band pickling
    (small/ineligible result, ring unavailable, or every slot still
    pinned by parent-side views)."""
    try:
        data, views = _encode_oob(result)
    except Exception:
        return None
    if not views:
        return None
    total = sum(v.nbytes for v in views)
    if total < PROC_RING_MIN:
        return None
    ring = rring_box[0]
    if ring is None or ring.slot_bytes < total:
        new = _ShmRing.create(total, PROC_RING_SLOTS)
        if new is None:
            return None
        if ring is not None:
            # parent's mapping (and served views) keep the old pages
            # alive; the parent unlinks it as a safety net at shutdown
            ring.view = None
            try:
                ring.shm.close()
            except BufferError:  # pragma: no cover
                pass
        rring_box[0] = ring = new
    got = ring.alloc.acquire(("parent",))
    if got is None:
        rring_box[1] += 1  # ring-full stall; socket... pipe fallback
        return None
    slot, gen = got
    pos = slot * ring.slot_bytes
    lens = []
    for v in views:
        n = v.nbytes
        ring.view[pos:pos + n] = np.frombuffer(v, np.uint8)
        lens.append(n)
        pos += n
    return (
        _MARK_RESULT, ring.name, ring.slot_bytes, ring.slots, slot,
        gen, tuple(lens), data,
    )


def _worker_main(
    i: int, conn, work_fn: WorkFn, delay_fn: DelayFn | None,
    telemetry: bool = False, shm_rings: bool = True,
) -> None:
    """Worker process entry: the reference's receive -> stall -> compute ->
    send loop (§3.2) over a pipe instead of MPI point-to-point.

    Round 12: with ``shm_rings`` (the coordinator's default), bulk
    ndarray payloads arrive as read-only views over a shared broadcast
    ring (resolved from a tiny control frame) and bulk results leave
    through this worker's own result ring — the pipe carries only
    control frames and slot-release acks in both directions.

    ``telemetry=True`` (set when the coordinator was constructed with a
    ``registry``) keeps a worker-local
    :class:`~..obs.aggregate.WorkerTelemetry` whose snapshot rides each
    result tuple as a 6th element — tasks/errors counters, compute-wall
    histogram, per-task spans, and the worker-clock stamps the
    coordinator's clock aligner pairs with its own. One final frame is
    sent on the shutdown drain so end-of-run telemetry is not lost."""
    tele = None
    if telemetry:
        from ..obs.aggregate import WorkerTelemetry

        tele = WorkerTelemetry(i)
    brings: dict = {}        # attached broadcast rings, name -> segment
    pending_acks: list = []  # broadcast-slot releases owed to the
    # parent (view finalizers append; NEVER rebind this list — the
    # finalizer callbacks hold it)
    rring_box = [None, 0]    # [result _ShmRing | None, stall count]
    try:
        while True:
            msg = conn.recv()
            t_recv_w = time.perf_counter() if tele is not None else 0.0
            if msg is None:  # shutdown sentinel (control channel)
                if tele is not None:
                    # drain frame: the last inter-result telemetry
                    conn.send((-1, -1, "tele", tele.snapshot(), -1))
                break
            if (
                isinstance(msg, tuple) and len(msg) == 2
                and msg[0] == _MARK_ACK
            ):
                ring = rring_box[0]
                for name, slot, gen in msg[1]:
                    if ring is not None and ring.name == name:
                        ring.alloc.release(slot, gen, "parent")
                continue
            seq, payload, epoch, tag = msg
            stall = 0.0
            if delay_fn is not None:
                d = float(delay_fn(i, epoch))
                if d > 0:
                    stall = d
                    time.sleep(d)
            t0 = time.perf_counter() if tele is not None else 0.0
            try:
                if (
                    isinstance(payload, tuple) and payload
                    and payload[0] == _MARK_BCAST
                ):
                    # resolve INSIDE the capture: a lost segment must
                    # ship back as an error, not kill the worker
                    payload = _child_resolve_bcast(
                        payload, brings, pending_acks
                    )
                result = work_fn(i, payload, epoch)
                payload = None  # release the slot view promptly
                marker = (
                    _child_ring_result(rring_box, result)
                    if shm_rings else None
                )
                if marker is not None:
                    result = marker
                out = (seq, epoch, "ok", result, tag)
                failed = False
            except BaseException as e:
                out = (
                    seq, epoch, "error",
                    (type(e).__name__, str(e), traceback.format_exc()),
                    tag,
                )
                failed = True
            if pending_acks or rring_box[1]:
                recs = pending_acks[:]
                del pending_acks[:len(recs)]
                if rring_box[1]:
                    recs.append(("", -1, rring_box[1]))  # stall report
                    rring_box[1] = 0
                conn.send((_MARK_ACK, recs))
            frame = None
            if tele is not None:
                t1 = time.perf_counter()
                tele.task_done(epoch, t0, t1, error=failed, stall=stall)
                # t_send_w stamped by snapshot construction time — the
                # tiny build cost lands in the transport-delay half,
                # where the min-delay offset filter absorbs it
                frame = tele.snapshot(pair=(seq, t_recv_w, t1))
                out = out + (frame,)
            try:
                conn.send(out)
            except Exception as e:  # result not picklable
                err = (
                    seq, epoch, "error",
                    (type(e).__name__,
                     f"worker result could not be serialized: {e}", ""),
                    tag,
                )
                try:
                    # snapshot() drained the spans destructively;
                    # reattach the SAME frame so the failing task's
                    # span and clock pair survive — the postmortem
                    # case needs them most
                    conn.send(err if frame is None else err + (frame,))
                except Exception:
                    # the frame itself held the unpicklable value (a
                    # custom span arg): the error result must still
                    # reach the coordinator, not kill the worker
                    conn.send(err)
    except (EOFError, OSError, KeyboardInterrupt):
        pass
    finally:
        if rring_box[0] is not None:
            rring_box[0].destroy()  # parent holds its own mapping for
            # any still-pinned views; unlink here frees the name (the
            # parent's shutdown unlink is the crash-path safety net)
        conn.close()


class ProcessBackend(SlotBackend):
    """n spawned worker processes computing ``work_fn(i, payload, epoch)``.

    The payload snapshot the reference takes via ``isendbufs[i] .= sendbuf``
    (src/MPIAsyncPools.jl:130) happens here by construction: the payload
    is copied out of the caller's buffer at dispatch time (into the
    shared ring, or by pickling), so in-flight sends survive caller
    mutation. numpy arrays cross zero-conversion; jax arrays are
    converted to numpy at dispatch (device buffers are not picklable).

    Round 12 (``shm_rings=True``, the default): ndarray payloads and
    results of >= 64 KiB ride ``multiprocessing.shared_memory`` rings
    as pickle protocol-5 out-of-band buffers — ONE memcpy into a ring
    slot per broadcast (shared across all n workers), results
    reconstructed as zero-copy views over the worker's result ring;
    the pipes carry only small control frames and slot-release acks.
    Consequence: bulk arrays now arrive as **read-only views** on both
    sides (the native backend's long-standing contract) — a work_fn
    that mutates its payload in place gets a loud ``ValueError``
    instead of a private copy. Pass ``shm_rings=False`` for the
    classic all-in-band pickling (and its mutable private copies).

    Parameters
    ----------
    work_fn:
        Picklable worker computation ``(worker_index, payload, epoch) ->
        result``.
    n_workers:
        Pool size (= number of spawned processes).
    delay_fn:
        Picklable deterministic latency injection, seconds as a function
        of ``(worker_index, epoch)``, applied *inside* the worker process.
    mp_context:
        ``multiprocessing`` start method; ``"spawn"`` (default) is safe
        with JAX/threads in the coordinator, ``"fork"`` is faster to boot
        for pure-numpy workers.
    registry:
        Opt-in cross-process telemetry (the obs/ contract — None = dark,
        zero cost): worker processes keep a local registry whose
        snapshots piggyback on result frames and merge here under a
        ``worker="<rank>"`` label with counter-delta semantics across
        respawns; worker spans land clock-aligned in
        ``self.aggregator.recorders()`` (one Perfetto pid per worker
        process — :mod:`..obs.aggregate`).
    flight:
        Optional :class:`~..obs.FlightRecorder`: merged worker spans are
        mirrored into the ring so a hang postmortem shows what every
        worker process was doing last.
    exporter:
        Optional :class:`~..obs.ObsServer`: registers the pool's
        worker-deadness health check (``/healthz`` flips when a worker
        dies, recovers after :meth:`respawn`) and the aggregator's
        per-worker trace sources.
    """

    def __init__(
        self,
        work_fn: WorkFn,
        n_workers: int,
        *,
        delay_fn: DelayFn | None = None,
        mp_context: str = "spawn",
        join_timeout: float = 5.0,
        shm_rings: bool = True,
        registry=None,
        flight=None,
        exporter=None,
    ):
        super().__init__(n_workers)
        self.work_fn = work_fn
        self.delay_fn = delay_fn
        self._join_timeout = join_timeout
        self._closed = False
        self._dead = [False] * self.n_workers
        self._send_lock = threading.Lock()
        self._mp_context = mp_context
        ctx = mp.get_context(mp_context)
        self.aggregator = None
        if registry is not None or flight is not None:
            from ..obs.aggregate import TelemetryAggregator

            self.aggregator = TelemetryAggregator(
                registry, flight=flight
            )
        # round-12 zero-copy pipe transport state (see class docstring;
        # shm_rings=False restores the classic everything-in-band
        # pickling, including the mutable-payload-copy semantics).
        # Linux-only: the attach side maps segments via /dev/shm (the
        # tracker-safe path), which macOS/Windows shm does not expose —
        # ProcessBackend is the portable fallback backend, so elsewhere
        # it stays the classic pickling transport it always was.
        import sys as _sys

        self._shm_rings = bool(shm_rings) and _sys.platform == "linux"
        self._ring_lock = threading.Lock()  # allocator/ring state is
        # shared between the coordinator thread and reader threads
        self._bring: "_ShmRing | None" = None
        self._bring_retired: list[_ShmRing] = []
        self._pick_epoch = None   # asyncmap epoch cache (begin_epoch)
        self._pick_src = None
        self._pick_marker = None
        # per-worker: result-ack pending lists (finalizers append —
        # cleared in place, never rebound), attached result-ring
        # segments, and every result-ring name ever seen (crash-path
        # unlink safety net)
        self._rack_pending: list[list] = [[] for _ in range(self.n_workers)]
        self._rring_maps: list[dict] = [{} for _ in range(self.n_workers)]
        self._rring_names: list[set] = [set() for _ in range(self.n_workers)]
        self.ring_stats = {
            "bcast_bytes": 0, "result_bytes": 0, "stalls": 0,
        }
        self._registry = registry
        self._rstats_last = dict(self.ring_stats)
        if registry is not None:
            self._m_bcast = registry.counter(
                "transport_zero_copy_bytes_total",
                help="payload bytes served without a userspace copy",
                path="pipe_bcast",
            )
            self._m_result = registry.counter(
                "transport_zero_copy_bytes_total",
                help="payload bytes served without a userspace copy",
                path="pipe_result",
            )
            self._m_stalls = registry.counter(
                "transport_ring_full_stalls_total",
                help="allocations that fell back to in-band pickling "
                "because every slot was pinned",
                side="pipe",
            )
        self._conns = [None] * self.n_workers
        self._procs = [None] * self.n_workers
        self._readers = [None] * self.n_workers
        for i in range(self.n_workers):
            self._spawn_worker(i)
        if exporter is not None:
            exporter.register_backend(self)

    def _spawn_worker(self, i: int) -> None:
        """Start (or restart) worker process i and its reader thread."""
        ctx = mp.get_context(self._mp_context)
        parent, child = ctx.Pipe(duplex=True)
        proc = ctx.Process(
            target=_worker_main,
            args=(i, child, self.work_fn, self.delay_fn,
                  self.aggregator is not None, self._shm_rings),
            daemon=True,
            name=f"pool-proc-worker-{i}",
        )
        proc.start()
        child.close()  # parent keeps only its end; EOF works
        self._conns[i] = parent
        self._procs[i] = proc
        # _dead is written from reader threads too (_on_worker_death);
        # all its writers take the completion lock (GC005)
        with self._cond:
            self._dead[i] = False
        reader = threading.Thread(
            target=self._reader_loop, args=(i,), daemon=True,
            name=f"pool-proc-reader-{i}",
        )
        self._readers[i] = reader
        reader.start()

    def respawn(self, i: int) -> None:
        """Elastic recovery: replace a dead worker process with a fresh
        one on the same rank (the reference has no such capability — a
        dead rank is permanent and hangs ``Waitall!``, SURVEY §5). The
        rank becomes dispatchable again; the old reader thread has
        already exited on its pipe's EOF."""
        if self._closed:
            raise RuntimeError("backend has been shut down")
        if not self._dead[i] and self._procs[i].is_alive():
            raise RuntimeError(f"worker {i} is alive; nothing to respawn")
        if self._procs[i].is_alive():  # pragma: no cover - wedged worker
            self._procs[i].terminate()
        self._procs[i].join(timeout=self._join_timeout)
        old_reader = self._readers[i]
        self._conns[i].close()  # unblock the old reader if still parked
        if old_reader is not None:
            old_reader.join(timeout=self._join_timeout)
        self._spawn_worker(i)

    def reap(self, i: int) -> None:
        """Elastic shrink: deliberately retire worker process ``i`` —
        the pair of :meth:`respawn`, and the verb the fleet
        controller's pool scaler uses (``fleet/failover.py``). The
        worker gets the shutdown sentinel (clean exit, telemetry
        drained), is terminated if it lingers, and the rank reads as
        dead (:meth:`dead_workers`) until a later :meth:`respawn`
        brings a fresh incarnation back. An in-flight dispatch fails
        with ``WorkerProcessDied`` exactly like a crash would — reap
        at an epoch boundary (after ``waitall``) to retire a rank with
        nothing outstanding. Idempotent while already dead."""
        if self._closed:
            raise RuntimeError("backend has been shut down")
        with self._cond:
            if self._dead[i]:
                return
        try:
            with self._send_lock:
                self._conns[i].send(None)
        except (BrokenPipeError, OSError):
            pass
        proc = self._procs[i]
        proc.join(timeout=self._join_timeout)
        if proc.is_alive():  # pragma: no cover - wedged worker
            proc.terminate()
            proc.join(timeout=self._join_timeout)
        # the reader thread stamps _dead on the pipe's EOF
        # (_on_worker_death) and fails anything outstanding; wait for
        # the stamp so dead_workers() is truthful the moment reap
        # returns (the cond wakes on its own timeout — no notifier
        # needed on the nothing-outstanding path)
        deadline = time.monotonic() + self._join_timeout
        with self._cond:
            while not self._dead[i] and time.monotonic() < deadline:
                self._cond.wait(0.05)
            if not self._dead[i]:  # pragma: no cover - wedged reader
                raise RuntimeError(
                    f"worker {i} terminated but its reader never "
                    "stamped the rank dead — dead_workers() would "
                    "lie, so reap refuses to return"
                )

    # -- coordinator-side completion pump ---------------------------------
    def _reader_loop(self, i: int) -> None:
        conn = self._conns[i]
        agg = self.aggregator
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                self._on_worker_death(i, conn)
                return
            if msg is None:
                return
            if (
                isinstance(msg, tuple) and len(msg) == 2
                and msg[0] == _MARK_ACK
            ):
                # worker released broadcast-ring slots (or reports
                # ring-full stalls: name "", slot -1, count in gen)
                with self._ring_lock:
                    for name, slot, gen in msg[1]:
                        if slot == -1 and name == "":
                            self.ring_stats["stalls"] += int(gen)
                            continue
                        for ring in (
                            [self._bring] if self._bring is not None
                            else []
                        ) + self._bring_retired:
                            if ring.name == name:
                                ring.alloc.release(slot, gen, i)
                    self._gc_retired_locked()
                continue
            t_recv_c = (
                time.perf_counter() if agg is not None else None
            )
            seq, epoch, kind, payload, tag, *tele = msg
            if kind == "tele":  # shutdown-drain telemetry frame
                if agg is not None:
                    agg.merge(i, payload)
                continue
            if agg is not None and tele:
                # merge BEFORE completing: a scrape racing the harvest
                # sees the worker series of every result the pool has
                agg.merge(i, tele[0], t_recv_c=t_recv_c)
            if kind == "error":
                exc_type, message, tb = payload
                payload = WorkerError(
                    i, epoch, RemoteWorkerError(exc_type, message, tb)
                )
            elif (
                isinstance(payload, tuple) and payload
                and payload[0] == _MARK_RESULT
            ):
                payload = self._resolve_result(i, epoch, payload)
            self._complete(i, seq, payload, tag)
            # opportunistic ack flush: result views released since the
            # last dispatch go back now, not an epoch later (finalizers
            # only append to the pending list — no lock hazards)
            self._flush_result_acks(i)

    def _on_worker_death(self, i: int, conn) -> None:
        """Fail the outstanding task (if any) so waits don't hang — the
        capability the reference lacks (dead rank hangs ``Waitall!``)."""
        if self._conns[i] is not conn:
            return  # stale EOF from a pre-respawn incarnation
        # fail the outstanding task on EVERY tag channel: the process is
        # gone, so no channel's completion can ever arrive. The _dead
        # stamp shares the same lock acquisition — this runs on the
        # reader thread while _start/_spawn_worker write the flag from
        # the coordinator (GC005 lock discipline)
        with self._cond:
            self._dead[i] = True
            pending = [
                (tag, slots[i].seq)
                for tag, slots in self._channels.items()
                if slots[i].outstanding and not slots[i].done
            ]
        # a dead worker never acks: reap its broadcast-slot pins so the
        # ring drains (its own result ring died with it). Taken OUTSIDE
        # _cond — lock order is always _ring_lock alone or _cond alone.
        with self._ring_lock:
            if self._bring is not None:
                self._bring.alloc.release_holder_everywhere(i)
            for ring in self._bring_retired:
                ring.alloc.release_holder_everywhere(i)
            self._gc_retired_locked()
        del self._rack_pending[i][:]
        if not self._closed:
            for tag, seq in pending:
                self._complete(
                    i, seq, WorkerError(i, -1, WorkerProcessDied(i)), tag
                )

    def dead_workers(self) -> list[int]:
        """Ranks whose worker process is currently dead (not yet
        respawned) — the ``/healthz`` pool check reads this."""
        with self._cond:
            return [i for i, d in enumerate(self._dead) if d]

    # -- zero-copy ring plumbing ------------------------------------------
    def _resolve_result(self, i: int, epoch: int, marker):
        """Reconstruct a worker's result from its result ring: attach
        the segment on first sight, unpickle over read-only slot views
        (zero copy), register the slot-release ack that fires when the
        harvested arrays die."""
        _, name, slot_bytes, slots, slot, gen, lens, data = marker
        cache = self._rring_maps[i]
        entry = cache.get(name)
        if entry is None:
            try:
                entry = _attach_shm(name)
            except OSError as e:
                return WorkerError(i, epoch, WorkerProcessDied(i)) if (
                    self._dead[i]
                ) else WorkerError(i, epoch, e)
            cache[name] = entry
            with self._ring_lock:
                self._rring_names[i].add(name)
        views = _serve_slot_views(
            entry[1], slot * slot_bytes, lens,
            self._queue_result_ack, i, (name, slot, gen),
        )
        with self._ring_lock:
            self.ring_stats["result_bytes"] += sum(lens)
        return pickle.loads(data, buffers=views)

    def _queue_result_ack(self, i: int, rec) -> None:
        # finalizer callback (any thread): append only — the flush
        # happens at safe points (dispatch / post-complete), never here
        self._rack_pending[i].append(rec)

    def _flush_result_acks(self, i: int) -> None:
        pend = self._rack_pending[i]
        if not pend:
            return
        recs = pend[:]
        del pend[:len(recs)]
        try:
            with self._send_lock:
                self._conns[i].send((_MARK_ACK, recs))
        except (BrokenPipeError, OSError, AttributeError):
            pass  # worker gone; its ring died with it

    def _bcast_ctrl(self, i: int, sendbuf, payload, epoch: int):
        """Stage ``payload`` in the shared broadcast ring and return
        the control marker for worker ``i`` (or None = send in-band).
        Inside an asyncmap epoch (begin_epoch) the encode + slot write
        happens ONCE and later dispatches only add their rank as a
        holder — one memcpy per broadcast, like the native arena."""
        cacheable = self._pick_epoch == int(epoch)
        if cacheable and self._pick_src is sendbuf and (
            self._pick_marker is not None
        ):
            marker = self._pick_marker
            with self._ring_lock:
                ring = self._bring
                if ring is not None and ring.name == marker[1]:
                    ring.alloc.add_holder(marker[4], marker[5], i)
                    return marker
            return None  # ring replaced mid-epoch; re-encode
        try:
            data, views = _encode_oob(payload)
        except Exception:
            return None
        if not views:
            return None
        total = sum(v.nbytes for v in views)
        if total < PROC_RING_MIN:
            return None
        with self._ring_lock:
            ring = self._bring
            if ring is None or ring.slot_bytes < total:
                new = _ShmRing.create(total, PROC_RING_SLOTS)
                if new is None:
                    return None
                if ring is not None:
                    self._bring_retired.append(ring)
                self._bring = ring = new
            holders = ("coord", i) if cacheable else (i,)
            got = ring.alloc.acquire(holders)
            if got is None:
                self.ring_stats["stalls"] += 1
                return None
            slot, gen = got
            self.ring_stats["bcast_bytes"] += total
            self._gc_retired_locked()
        pos = slot * ring.slot_bytes  # slot exclusively ours: write
        lens = []                     # outside the lock
        for v in views:
            n = v.nbytes
            ring.view[pos:pos + n] = np.frombuffer(v, np.uint8)
            lens.append(n)
            pos += n
        marker = (
            _MARK_BCAST, ring.name, ring.slot_bytes, ring.slots, slot,
            gen, tuple(lens), data,
        )
        if cacheable:
            with self._ring_lock:
                # a replaced cached marker (direct dispatch of a
                # DIFFERENT buffer at the same epoch) must release its
                # coord pin, or the old slot strands pinned forever
                self._release_pick_locked()
            self._pick_src = sendbuf
            self._pick_marker = marker
        return marker

    def _release_pick_locked(self) -> None:
        """Release the cached marker's ``"coord"`` hold against
        WHICHEVER ring owns it — the current ring, or a retired one
        when the ring grew mid-epoch (caller holds ``_ring_lock``)."""
        marker = self._pick_marker
        if marker is None:
            return
        for ring in (
            [self._bring] if self._bring is not None else []
        ) + self._bring_retired:
            if ring.name == marker[1]:
                ring.alloc.release(marker[4], marker[5], "coord")
                break
        self._gc_retired_locked()

    def _gc_retired_locked(self) -> None:
        """Unlink superseded broadcast rings once drained. The
        ``_locked`` suffix is the contract: EVERY caller already holds
        ``_ring_lock`` (taking it here would self-deadlock), which is
        what the GC005 suppression below records."""
        still = []
        for ring in self._bring_retired:
            if ring.alloc.pinned == 0:
                ring.destroy()
            else:
                still.append(ring)
        self._bring_retired[:] = still  # graftcheck: disable=GC005

    def _publish_ring_stats(self) -> None:
        """Mirror ring stats into the opt-in registry (counter deltas).
        Callers guard on ``self._registry is not None``."""
        with self._ring_lock:
            s = dict(self.ring_stats)
        last = self._rstats_last
        if s["bcast_bytes"] > last["bcast_bytes"]:
            self._m_bcast.inc(s["bcast_bytes"] - last["bcast_bytes"])
        if s["result_bytes"] > last["result_bytes"]:
            self._m_result.inc(s["result_bytes"] - last["result_bytes"])
        if s["stalls"] > last["stalls"]:
            self._m_stalls.inc(s["stalls"] - last["stalls"])
        self._rstats_last = s

    def begin_epoch(self, epoch: int) -> None:
        # arm the one-encode-per-broadcast cache for this asyncmap call
        # (native backend discipline: direct Backend-API dispatches
        # outside an epoch window always re-encode)
        self.end_epoch()
        self._pick_epoch = int(epoch)

    def end_epoch(self) -> None:
        if self._pick_marker is not None:
            with self._ring_lock:
                self._release_pick_locked()
        self._pick_epoch = None
        self._pick_src = None
        self._pick_marker = None

    # -- SlotBackend surface ----------------------------------------------
    def _start(self, i: int, sendbuf, epoch: int, seq: int, tag: int) -> None:
        if self._closed:
            raise RuntimeError("backend has been shut down")
        if self._dead[i]:  # fail fast instead of writing to a broken pipe
            self._complete(
                i, seq, WorkerError(i, epoch, WorkerProcessDied(i)), tag
            )
            return
        payload = sendbuf
        if hasattr(payload, "__array__") and not isinstance(payload, np.ndarray):
            payload = np.asarray(payload)  # device arrays are not picklable
        if self._shm_rings:
            ctrl = self._bcast_ctrl(i, sendbuf, payload, epoch)
            if ctrl is not None:
                payload = ctrl
        if self.aggregator is not None:
            # half of a clock-offset sample; the worker's matching
            # stamps ride back on the result frame
            self.aggregator.note_dispatch(i, seq, time.perf_counter())
        if self._registry is not None:
            self._publish_ring_stats()
        self._flush_result_acks(i)
        try:
            with self._send_lock:
                self._conns[i].send((seq, payload, epoch, tag))
        except (BrokenPipeError, OSError):
            with self._cond:  # racing _on_worker_death's stamp (GC005)
                self._dead[i] = True
            self._complete(
                i, seq, WorkerError(i, epoch, WorkerProcessDied(i)), tag
            )

    def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        for i, conn in enumerate(self._conns):
            try:
                with self._send_lock:
                    conn.send(None)
            except (BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=self._join_timeout)
        for proc in self._procs:
            if proc.is_alive():  # pragma: no cover - stuck worker
                proc.terminate()
                proc.join(timeout=self._join_timeout)  # reap before close
        for proc in self._procs:
            if not proc.is_alive():
                proc.close()  # release the spawn sentinel fds deterministically
        if self.aggregator is not None:
            # the reader threads are the ones merging the workers'
            # shutdown-drain telemetry frames; the workers have exited
            # (pipes at EOF), so the readers finish promptly — join
            # them BEFORE closing the conns, or the final deltas race
            # the close and are lost nondeterministically (the pipe
            # twin of the native backend's _drain_obs)
            for reader in self._readers:
                if reader is not None:
                    reader.join(timeout=self._join_timeout)
        # zero-copy teardown: the coordinator owns the broadcast rings
        # (unlink them); result rings belong to the workers, who unlink
        # on clean exit — unlink any name still present as the
        # crash-path safety net (hard-killed workers skip finally)
        with self._ring_lock:
            if self._bring is not None:
                self._bring.destroy()
                self._bring = None
            for ring in self._bring_retired:
                ring.destroy()
            self._bring_retired = []
        for i in range(self.n_workers):
            for name in list(self._rring_names[i]):
                _unlink_shm_quiet(name)
        for conn in self._conns:
            conn.close()
