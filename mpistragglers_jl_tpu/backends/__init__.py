from .base import Backend, SlotBackend, WorkerError, WorkerFailure
from .local import LocalBackend

__all__ = [
    "Backend",
    "SlotBackend",
    "WorkerError",
    "WorkerFailure",
    "LocalBackend",
    "XLADeviceBackend",
]


def __getattr__(name):
    # lazy: importing the XLA backend pulls in jax (and TPU plugin
    # registration); LocalBackend-only use stays numpy-only
    if name == "XLADeviceBackend":
        from .xla import XLADeviceBackend

        return XLADeviceBackend
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
