from .base import Backend, SlotBackend, WorkerError, WorkerFailure
from .local import LocalBackend
from .process import ProcessBackend, RemoteWorkerError, WorkerProcessDied

__all__ = [
    "Backend",
    "SlotBackend",
    "WorkerError",
    "WorkerFailure",
    "LocalBackend",
    "ProcessBackend",
    "RemoteWorkerError",
    "WorkerProcessDied",
    "XLADeviceBackend",
]


def __getattr__(name):
    # lazy: importing the XLA backend pulls in jax (and TPU plugin
    # registration); LocalBackend-only use stays numpy-only
    if name == "XLADeviceBackend":
        from .xla import XLADeviceBackend

        return XLADeviceBackend
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
