from .base import Backend, SlotBackend
from .local import LocalBackend, WorkerFailure

__all__ = ["Backend", "SlotBackend", "LocalBackend", "WorkerFailure"]
