from .base import Backend, SlotBackend, WorkerError, WorkerFailure
from .local import LocalBackend
from .process import ProcessBackend, RemoteWorkerError, WorkerProcessDied

__all__ = [
    "Backend",
    "SlotBackend",
    "WorkerError",
    "WorkerFailure",
    "LocalBackend",
    "ProcessBackend",
    "RemoteWorkerError",
    "WorkerProcessDied",
    "XLADeviceBackend",
    "NativeProcessBackend",
]


def __getattr__(name):
    # lazy: importing the XLA backend pulls in jax (and TPU plugin
    # registration), and the native backend compiles C++ on first use;
    # LocalBackend-only use stays numpy-only
    if name == "XLADeviceBackend":
        from .xla import XLADeviceBackend

        return XLADeviceBackend
    if name == "NativeProcessBackend":
        from .native import NativeProcessBackend

        return NativeProcessBackend
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
