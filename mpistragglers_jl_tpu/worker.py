"""Standalone worker entry point for multi-host pools.

The reference's multi-process story is ``mpiexec`` launching every rank
on hosts listed in a hostfile (test/runtests.jl:17); the equivalent here
is one coordinator binding the native transport on TCP and each remote
host launching workers against it:

    # on the coordinator host
    backend = NativeProcessBackend(work_fn, n, spawn=False,
                                   address="tcp://0.0.0.0:5555")

    # on each worker host
    python -m mpistragglers_jl_tpu.worker \
        --address tcp://coordinator-host:5555 --rank 3 \
        --work mypkg.mymod:work_fn

The worker loop is the reference's receive -> stall -> compute -> send
convention (SURVEY §3.2, examples/iterative_example.jl:55-82) made a
first-class program: frames in, pickled payloads through ``work_fn``,
results (or captured exceptions) back, shutdown on the control frame.
``--work`` takes ``module:attribute``; the module must be importable on
the worker host (install your package or set PYTHONPATH).
"""

from __future__ import annotations

import argparse
import importlib
import os
import time
import traceback

from .backends.base import DelayFn
from .native import codec
from .native import transport as T

__all__ = ["run_worker", "resolve_callable", "main"]


def run_worker(
    address: str,
    rank: int,
    work_fn,
    delay_fn: DelayFn | None = None,
    *,
    token: bytes = b"",
    connect_timeout: float = 30.0,
    telemetry: bool = False,
    zero_copy: bool = True,
) -> None:
    """Connect to the coordinator and serve until shutdown.

    ``work_fn(rank, payload, epoch) -> result`` with picklable results;
    exceptions are captured and shipped back as failures, not lost the
    way reference worker assertions die inside mpiexec (SURVEY §4).

    Array payloads arrive as **read-only zero-copy views** of transport
    memory (socket frame, shared-memory region, or a broadcast-arena
    slot — native/codec.py); copy before mutating in place. Views may
    be retained indefinitely: a shared-memory region stays mapped (and
    an arena/ring slot stays unreclaimed) for as long as any view of it
    is alive — eviction and slot reuse are deferred, never dangling.
    ``zero_copy=False`` turns off both the result ring and (on the
    coordinator side, via the backend's matching flag) the arena.

    The connect retries with backoff until ``connect_timeout``: a worker
    that races the coordinator's bind, or whose hello lands while the
    coordinator is busy reaccepting a different rank, re-attempts
    instead of exiting and permanently losing the rank. ``token`` is the
    shared auth secret (must match the coordinator's, if it has one).

    ``telemetry=True`` (coordinator-requested via
    ``NativeProcessBackend(registry=...)``, or ``--telemetry`` on the
    CLI) keeps a worker-local
    :class:`~.obs.aggregate.WorkerTelemetry`; its snapshot follows each
    result as a standalone frame on the reserved
    :data:`~.obs.aggregate.OBS_TAG` channel (plus one final frame
    before shutdown), which an aggregating coordinator merges and a
    dark one drops by the tag's seq guard — the frames are invisible to
    the pool either way.
    """
    tele = None
    if telemetry:
        from .obs.aggregate import OBS_TAG, WorkerTelemetry

        tele = WorkerTelemetry(rank)
    w = _connect_retry(
        address, rank, token, connect_timeout,
        ring_min=T.RING_MIN if zero_copy else None,
    )
    try:
        while True:
            msg = w.recv()
            t_recv_w = time.perf_counter() if tele is not None else 0.0
            if msg is None or msg.kind == T.KIND_CONTROL:
                if tele is not None and msg is not None:
                    # shutdown drain: flush the last telemetry frame
                    p, b = codec.encode(tele.snapshot())
                    w.send2(p, b, seq=-1, tag=OBS_TAG)
                break  # coordinator gone, or shutdown broadcast
            failed = False
            t0 = 0.0
            stall = 0.0
            # routing echo saved up front so the frame itself can be
            # dropped the moment its payload is decoded
            seq_, epoch_, tag_ = msg.seq, msg.epoch, msg.tag
            try:
                # decoding is inside the capture: an undecodable payload
                # (e.g. a class not importable on this host — the common
                # multi-host failure) must ship back as an error, not
                # kill the worker without a diagnostic. Raw ndarray
                # payloads decode as zero-copy views (native/codec.py).
                payload = codec.decode(msg.payload, msg.body)
                msg = None  # the view chain roots in the payload now
                if delay_fn is not None:
                    d = float(delay_fn(rank, epoch_))
                    if d > 0:
                        stall = d
                        time.sleep(d)
                t0 = time.perf_counter()
                prefix, body = codec.encode(
                    work_fn(rank, payload, epoch_)
                )
                kind = T.KIND_DATA
            except BaseException as e:
                failed = True
                prefix, body = codec.encode(
                    (type(e).__name__, str(e), traceback.format_exc())
                )
                kind = T.KIND_ERROR
            # drop the payload view before sending: an arena slot is
            # only reclaimable once its views die. (For an echo-style
            # work_fn the RESULT may itself be the payload view, so the
            # chain fully dies only at `body = None` below — either
            # way, before the next recv, whose first act is to flush
            # the queued release acks.)
            payload = None
            msg = None
            # echo seq AND tag: the coordinator routes completions to
            # the (rank, tag) channel the dispatch was posted on. Data
            # results >= RING_MIN ride this worker's persistent result
            # ring (one memcpy into shared pages; only a control frame
            # crosses the socket); everything else is a two-buffer
            # socket send written straight from its buffer.
            if not w.send_result(
                prefix, body, seq=seq_, epoch=epoch_, tag=tag_,
                kind=kind,
            ):
                break
            prefix = body = None  # release NOW: the next recv's ack
            # flush ships the slot release in this same frame boundary
            if tele is not None:
                t1 = time.perf_counter()
                tele.task_done(
                    epoch_, t0 or t_recv_w, t1, error=failed,
                    stall=stall,
                )
                try:
                    p, b = codec.encode(
                        tele.snapshot(pair=(seq_, t_recv_w, t1))
                    )
                except Exception:
                    # span args are sanitized at record time, so this
                    # is belt-and-braces: a pathological frame must
                    # drop ITSELF, never kill a worker whose every
                    # task computed fine
                    continue
                if not w.send2(
                    p, b, seq=seq_, epoch=epoch_, tag=OBS_TAG
                ):
                    break
    finally:
        w.close()


def _connect_retry(
    address: str, rank: int, token: bytes, timeout: float,
    ring_min: int | None = T.RING_MIN,
) -> T.Worker:
    deadline = time.perf_counter() + timeout
    delay = 0.05
    while True:
        try:
            return T.Worker(address, rank, token=token, ring_min=ring_min)
        except T.TransportError:
            left = deadline - time.perf_counter()
            if left <= 0:
                raise
            time.sleep(min(delay, left))
            delay = min(delay * 2, 1.0)


def resolve_callable(spec: str):
    """Import ``module.path:attribute`` and return the attribute."""
    if ":" not in spec:
        raise ValueError(
            f"callable spec must be 'module:attribute', got {spec!r}"
        )
    mod_name, attr = spec.split(":", 1)
    obj = importlib.import_module(mod_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    if not callable(obj):
        raise TypeError(f"{spec} resolved to non-callable {obj!r}")
    return obj


def parse_ranks(spec: str) -> list[int]:
    """Parse a rank spec: ``3``, ``0-7``, or ``0,2,5-7``."""
    out: list[int] = []
    for part in spec.split(","):
        part = part.strip()
        if "-" in part:
            lo, hi = part.split("-", 1)
            lo, hi = int(lo), int(hi)
            if hi < lo:
                raise ValueError(f"descending rank range {part!r}")
            out.extend(range(lo, hi + 1))
        else:
            out.append(int(part))
    if len(set(out)) != len(out):
        raise ValueError(f"duplicate ranks in {spec!r}")
    return out


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m mpistragglers_jl_tpu.worker",
        description="Serve pool worker(s) over the native transport.",
    )
    ap.add_argument(
        "--address", required=True,
        help="coordinator address: tcp://host:port or a unix socket path",
    )
    ap.add_argument(
        "--rank", "--ranks", dest="ranks", required=True,
        help="pool index, range, or list: '3', '0-7', '0,2,5-7' — one "
        "worker process per rank (a host serving several ranks needs "
        "only one command)",
    )
    ap.add_argument(
        "--work", required=True,
        help="work function as module:attribute, "
        "signature (rank, payload, epoch) -> result",
    )
    ap.add_argument(
        "--delay", default=None,
        help="optional delay_fn as module:attribute (straggler injection)",
    )
    ap.add_argument(
        "--auth-file", default=None,
        help="file holding the shared auth secret (the coordinator's "
        "`auth=` bytes); the MSGT_AUTH environment variable is the "
        "argv-invisible alternative. No flag/env = unauthenticated",
    )
    ap.add_argument(
        "--telemetry", action="store_true",
        help="keep a worker-local metrics registry and piggyback its "
        "snapshots on result frames (merged by a coordinator built "
        "with registry=; dropped harmlessly otherwise)",
    )
    ap.add_argument(
        "--no-zero-copy", action="store_true",
        help="disable this worker's shared-memory result ring (the "
        "copying socket sends only) — pair with the coordinator's "
        "zero_copy=False for a fully copying baseline; TCP workers "
        "are copying regardless",
    )
    args = ap.parse_args(argv)
    ranks = parse_ranks(args.ranks)
    token = _resolve_token(args.auth_file)
    # resolve in the parent too: a typo'd spec fails fast, before spawn
    work_fn = resolve_callable(args.work)
    delay_fn = resolve_callable(args.delay) if args.delay else None
    if len(ranks) == 1:
        run_worker(args.address, ranks[0], work_fn, delay_fn,
                   token=token, telemetry=args.telemetry,
                   zero_copy=not args.no_zero_copy)
        return
    # one OS process per rank (ranks must not share a Python process:
    # work_fn may hold the GIL, and per-rank crash isolation is the
    # point). Children get the SPEC STRINGS and re-resolve — resolved
    # callables may not survive spawn's pickle round-trip (bound
    # methods, decorated functions), and the strings always do.
    import multiprocessing as mp
    import signal

    ctx = mp.get_context("spawn")
    procs = [
        ctx.Process(
            target=_spawned_rank_main,
            args=(args.address, r, args.work, args.delay, token,
                  args.telemetry, not args.no_zero_copy),
            name=f"pool-cli-worker-{r}",
        )
        for r in ranks
    ]

    def _terminate(signum, frame):  # pragma: no cover - signal path
        for p in procs:
            if p.is_alive():
                p.terminate()

    # killing the one-command-per-host parent must not orphan the
    # per-rank children (a replacement command would find duplicate
    # live ranks)
    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    try:
        for p in procs:
            p.start()
        for p in procs:
            p.join()
    finally:
        for p in procs:
            if p.is_alive():  # pragma: no cover - abnormal exit
                p.terminate()
    failed = [p.name for p in procs if p.exitcode not in (0, None)]
    if failed:
        raise SystemExit(
            f"worker processes exited nonzero: {', '.join(failed)}"
        )


def _resolve_token(auth_file: str | None) -> bytes:
    """Auth secret from ``--auth-file`` (wins) or ``MSGT_AUTH``.

    The file is read verbatim except for one trailing newline (the
    editor artifact): secrets are arbitrary bytes, and a broad strip
    would corrupt any token that happens to start or end with a
    whitespace byte — HMAC then never matches and the worker is
    refused with no hint why.
    """
    if auth_file is not None:
        with open(auth_file, "rb") as f:
            data = f.read()
        if data.endswith(b"\n"):
            data = data[:-1]
        if data.endswith(b"\r"):
            data = data[:-1]
        return data
    env = os.environ.get("MSGT_AUTH")
    return env.encode() if env else b""


def _spawned_rank_main(
    address: str, rank: int, work_spec: str, delay_spec: str | None,
    token: bytes = b"", telemetry: bool = False, zero_copy: bool = True,
) -> None:
    """Child entry for multi-rank mode: resolve specs locally, serve."""
    run_worker(
        address,
        rank,
        resolve_callable(work_spec),
        resolve_callable(delay_spec) if delay_spec else None,
        token=token,
        telemetry=telemetry,
        zero_copy=zero_copy,
    )


if __name__ == "__main__":
    main()
