"""Standalone worker entry point for multi-host pools.

The reference's multi-process story is ``mpiexec`` launching every rank
on hosts listed in a hostfile (test/runtests.jl:17); the equivalent here
is one coordinator binding the native transport on TCP and each remote
host launching workers against it:

    # on the coordinator host
    backend = NativeProcessBackend(work_fn, n, spawn=False,
                                   address="tcp://0.0.0.0:5555")

    # on each worker host
    python -m mpistragglers_jl_tpu.worker \
        --address tcp://coordinator-host:5555 --rank 3 \
        --work mypkg.mymod:work_fn

The worker loop is the reference's receive -> stall -> compute -> send
convention (SURVEY §3.2, examples/iterative_example.jl:55-82) made a
first-class program: frames in, pickled payloads through ``work_fn``,
results (or captured exceptions) back, shutdown on the control frame.
``--work`` takes ``module:attribute``; the module must be importable on
the worker host (install your package or set PYTHONPATH).
"""

from __future__ import annotations

import argparse
import importlib
import pickle
import time
import traceback

from .backends.base import DelayFn
from .native import transport as T

__all__ = ["run_worker", "resolve_callable", "main"]


def run_worker(
    address: str,
    rank: int,
    work_fn,
    delay_fn: DelayFn | None = None,
) -> None:
    """Connect to the coordinator and serve until shutdown.

    ``work_fn(rank, payload, epoch) -> result`` with picklable results;
    exceptions are captured and shipped back as failures, not lost the
    way reference worker assertions die inside mpiexec (SURVEY §4).
    """
    w = T.Worker(address, rank)
    try:
        while True:
            msg = w.recv()
            if msg is None or msg.kind == T.KIND_CONTROL:
                break  # coordinator gone, or shutdown broadcast
            try:
                # deserialization is inside the capture: an unpicklable
                # payload (e.g. a class not importable on this host — the
                # common multi-host failure) must ship back as an error,
                # not kill the worker without a diagnostic
                payload = pickle.loads(msg.payload)
                if delay_fn is not None:
                    d = float(delay_fn(rank, msg.epoch))
                    if d > 0:
                        time.sleep(d)
                out = pickle.dumps(
                    work_fn(rank, payload, msg.epoch), protocol=5
                )
                kind = T.KIND_DATA
            except BaseException as e:
                out = pickle.dumps(
                    (type(e).__name__, str(e), traceback.format_exc()),
                    protocol=5,
                )
                kind = T.KIND_ERROR
            if not w.send(out, seq=msg.seq, epoch=msg.epoch, kind=kind):
                break
    finally:
        w.close()


def resolve_callable(spec: str):
    """Import ``module.path:attribute`` and return the attribute."""
    if ":" not in spec:
        raise ValueError(
            f"callable spec must be 'module:attribute', got {spec!r}"
        )
    mod_name, attr = spec.split(":", 1)
    obj = importlib.import_module(mod_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    if not callable(obj):
        raise TypeError(f"{spec} resolved to non-callable {obj!r}")
    return obj


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m mpistragglers_jl_tpu.worker",
        description="Serve one pool worker over the native transport.",
    )
    ap.add_argument(
        "--address", required=True,
        help="coordinator address: tcp://host:port or a unix socket path",
    )
    ap.add_argument("--rank", type=int, required=True, help="pool index")
    ap.add_argument(
        "--work", required=True,
        help="work function as module:attribute, "
        "signature (rank, payload, epoch) -> result",
    )
    ap.add_argument(
        "--delay", default=None,
        help="optional delay_fn as module:attribute (straggler injection)",
    )
    args = ap.parse_args(argv)
    run_worker(
        args.address,
        args.rank,
        resolve_callable(args.work),
        resolve_callable(args.delay) if args.delay else None,
    )


if __name__ == "__main__":
    main()
