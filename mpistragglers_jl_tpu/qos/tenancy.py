"""Tenant contracts: the per-tenant half of the QoS plane.

A tenant is a contract, not a code path: everything the fleet does
differently per tenant is read off one :class:`TenantContract` —
which SLO class it bought (``latency`` | ``throughput`` | ``batch``),
how much of the fleet it is entitled to (``weight``, the
deficit-round-robin share :class:`~.drr.DeficitScheduler` enforces),
how many tokens per second it may inject (``rate``/``burst``, a
:class:`TokenBucket` the router charges at submit), how many KV-cache
pages it may hold (``pages``, enforced at admission plan time with
COW-aware reclaim), and how many TTFT hedges it may have outstanding
(``hedges``, so one tenant's deadline panic cannot spend another's
slack).

Sheddability follows the class: a ``batch`` tenant over its token
budget is shed by name (``outcome == "shed"``, counted per tenant and
reason) — batch work retries; ``latency`` and ``throughput`` tenants
are never shed, they are *paced* instead (the deficit scheduler caps
their share, so an over-budget interactive tenant queues behind its
own weight rather than being dropped or starving anyone else).

Everything here is pure host bookkeeping on an INJECTED clock:
:meth:`TokenBucket.take` refills from the ``now`` the caller passes
(the router's clock — virtual seconds in sim, ``perf_counter`` live),
never from an OS clock, so a tenant-mixed day replays bit-identically
(graftcheck GC008 covers ``qos/`` like ``sim/`` and ``fleet/``).
"""

from __future__ import annotations

__all__ = [
    "SLO_CLASSES",
    "SHED_ORDER",
    "shed_rank",
    "TenantContract",
    "TenantRegistry",
    "TokenBucket",
]

SLO_CLASSES = ("latency", "throughput", "batch")

#: Overload shed order (chaos plane): when the fleet must drop work to
#: keep its queues bounded, classes are shed in THIS order — batch
#: first (its work retries), latency last (its work is a user staring
#: at a spinner). The budget door's shed rule (only ``batch`` sheds,
#: interactive classes are paced) is the rank-0 prefix of this order;
#: the router's soft overload ceiling sheds rank 0, and only the hard
#: ceiling — the bounded-queue guarantee under offered load past 1 —
#: sheds every rank, each by name.
SHED_ORDER = ("batch", "throughput", "latency")


def shed_rank(cls: str) -> int:
    """Position of an SLO class in :data:`SHED_ORDER` (0 sheds first).
    Unknown classes are refused by name, never ranked by guess."""
    try:
        return SHED_ORDER.index(cls)
    except ValueError:
        raise ValueError(
            f"unknown SLO class {cls!r}; choose one of {SLO_CLASSES}"
        ) from None


class TenantContract:
    """One tenant's contract (module docstring for field semantics).

    ``rate`` is a token-rate budget in tokens per clock second
    (``None`` = unlimited); ``burst`` is the bucket depth in tokens
    (default: one second of ``rate``). ``pages`` is the KV page-pool
    quota (``None`` = unlimited). ``spill_pages`` extends the page
    quota to the host-DRAM spill tier (cache/ package): how many of
    the tenant's evicted cold pages the fleet page store may keep
    resident at once (``None`` = unlimited — the store's own capacity
    still bounds it; enforced the same way as cold-page reclaim: the
    tenant's OWN oldest spilled page is evicted first). ``hedges``
    caps OUTSTANDING TTFT-hedge legs (``None`` = unlimited, ``0`` =
    never hedge). ``ttft_slo`` is the advertised first-token deadline
    the sweeps validate latency-class contracts against — a latency
    tenant without one is refused by ``sweep_tenant_weights``, never
    guessed.
    """

    __slots__ = ("name", "cls", "weight", "rate", "burst", "pages",
                 "spill_pages", "hedges", "ttft_slo")

    def __init__(self, name: str, *, cls: str = "throughput",
                 weight: float = 1.0, rate: float | None = None,
                 burst: float | None = None, pages: int | None = None,
                 spill_pages: int | None = None,
                 hedges: int | None = None,
                 ttft_slo: float | None = None):
        if not name or not isinstance(name, str):
            raise ValueError(f"tenant name must be a non-empty str, "
                             f"got {name!r}")
        if cls not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {cls!r} for tenant {name!r}; "
                f"choose one of {SLO_CLASSES}"
            )
        if not weight > 0:
            raise ValueError(
                f"tenant {name!r} weight must be > 0 (it is the DRR "
                f"share), got {weight}"
            )
        if rate is not None and not rate > 0:
            raise ValueError(
                f"tenant {name!r} token rate must be > 0 or None "
                f"(unlimited), got {rate}"
            )
        if burst is not None and rate is None:
            raise ValueError(
                f"tenant {name!r} has burst without rate: a bucket "
                "depth needs a refill rate"
            )
        if burst is not None and not burst > 0:
            raise ValueError(
                f"tenant {name!r} burst must be > 0, got {burst}"
            )
        if pages is not None and pages < 1:
            raise ValueError(
                f"tenant {name!r} page quota must be >= 1 or None "
                f"(unlimited), got {pages}"
            )
        if spill_pages is not None and spill_pages < 0:
            raise ValueError(
                f"tenant {name!r} spill-page quota must be >= 0 or "
                f"None (unlimited; 0 = never spill for this tenant), "
                f"got {spill_pages}"
            )
        if hedges is not None and hedges < 0:
            raise ValueError(
                f"tenant {name!r} hedge entitlement must be >= 0 or "
                f"None (unlimited), got {hedges}"
            )
        if ttft_slo is not None and not ttft_slo > 0:
            raise ValueError(
                f"tenant {name!r} ttft_slo must be > 0, got {ttft_slo}"
            )
        self.name = name
        self.cls = cls
        self.weight = float(weight)
        self.rate = None if rate is None else float(rate)
        self.burst = (
            self.rate if burst is None and rate is not None
            else (None if burst is None else float(burst))
        )
        self.pages = None if pages is None else int(pages)
        self.spill_pages = (
            None if spill_pages is None else int(spill_pages)
        )
        self.hedges = None if hedges is None else int(hedges)
        self.ttft_slo = None if ttft_slo is None else float(ttft_slo)

    @property
    def sheddable(self) -> bool:
        """Over-budget requests of this tenant may be dropped by name
        (``batch`` class only — batch work retries; interactive
        classes are paced by their DRR weight instead). Under fleet
        OVERLOAD the hard queue-depth ceiling sheds every class
        rather than queue unboundedly — but always in
        :data:`SHED_ORDER`, batch first, and always by name."""
        return self.cls == "batch"

    @property
    def shed_rank(self) -> int:
        """This contract's position in :data:`SHED_ORDER` (0 sheds
        first under overload)."""
        return shed_rank(self.cls)

    def bucket(self) -> "TokenBucket | None":
        """A fresh token bucket for this contract, or None when the
        contract carries no rate budget."""
        if self.rate is None:
            return None
        return TokenBucket(self.rate, self.burst)

    def __repr__(self) -> str:
        return (
            f"TenantContract({self.name!r}, cls={self.cls!r}, "
            f"weight={self.weight}, rate={self.rate}, "
            f"pages={self.pages}, hedges={self.hedges})"
        )


class TokenBucket:
    """Token-rate budget with refill, pure in the injected clock:
    ``take(cost, now)`` refills ``rate * (now - last_now)`` (capped at
    ``burst``) and then takes ``cost`` tokens if they are there. The
    first call anchors the refill clock — callers pass the SAME clock
    every time (the router's), which is what makes a tenant-mixed day
    replay bit-identically on :class:`~..sim.clock.VirtualClock`."""

    __slots__ = ("rate", "burst", "tokens", "_last")

    def __init__(self, rate: float, burst: float):
        if not rate > 0 or not burst > 0:
            raise ValueError(
                f"need rate > 0 and burst > 0, got ({rate}, {burst})"
            )
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)  # a fresh tenant starts full
        self._last: float | None = None

    def _refill(self, now: float) -> None:
        if self._last is None:
            self._last = now
            return
        if now > self._last:
            self.tokens = min(
                self.burst, self.tokens + self.rate * (now - self._last)
            )
            self._last = now

    def level(self, now: float) -> float:
        """Tokens available at ``now`` (refilled, nothing taken)."""
        self._refill(now)
        return self.tokens

    def take(self, cost: float, now: float) -> bool:
        """Charge ``cost`` tokens at ``now``; False (nothing taken)
        when the bucket cannot cover it — the caller's shed/pace
        decision point."""
        self._refill(now)
        if self.tokens + 1e-12 < cost:
            return False
        self.tokens -= cost
        return True


class TenantRegistry:
    """The fleet's tenant book: contracts by name, in registration
    order (the order is the DRR rotation order, so it is part of the
    deterministic-replay contract — never hash order). One registry is
    shared by every plane that reads contracts: the scheduler's
    deficit admission, the router's budget/hedge enforcement, and the
    sweeps' feasibility checks."""

    def __init__(self, contracts: "tuple[TenantContract, ...] | list" = ()):
        self._by_name: dict[str, TenantContract] = {}
        for c in contracts:
            self.add(c)

    def add(self, contract: TenantContract) -> TenantContract:
        if contract.name in self._by_name:
            raise ValueError(
                f"tenant {contract.name!r} already registered; update "
                "means a new registry, not a silent overwrite"
            )
        self._by_name[contract.name] = contract
        return contract

    def get(self, name: str) -> TenantContract:
        c = self._by_name.get(name)
        if c is None:
            raise KeyError(
                f"unknown tenant {name!r}: register a TenantContract "
                f"for it (known: {sorted(self._by_name)})"
            )
        return c

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __iter__(self):
        return iter(self._by_name.values())

    def __len__(self) -> int:
        return len(self._by_name)

    def names(self) -> list[str]:
        return list(self._by_name)

    def buckets(self) -> dict[str, TokenBucket]:
        """Fresh token buckets for every rate-budgeted tenant — the
        router builds its runtime charge state here."""
        out = {}
        for c in self._by_name.values():
            b = c.bucket()
            if b is not None:
                out[c.name] = b
        return out

    def aggregate_rate(self) -> float | None:
        """Sum of the registered token-rate budgets, or None when any
        tenant is unlimited (the sum is then unbounded) — the
        feasibility number ``sweep_tenant_weights`` checks against
        fleet capacity."""
        total = 0.0
        for c in self._by_name.values():
            if c.rate is None:
                return None
            total += c.rate
        return total

    def __repr__(self) -> str:
        return f"TenantRegistry({self.names()})"
