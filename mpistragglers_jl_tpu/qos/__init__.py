# graftcheck: hermetic-root  (GC001 walks this subpackage's closure as
# its own root: the QoS plane is pure stdlib — deciding WHO is served
# next must never require jax, an accelerator, or even numpy)
"""Multi-tenant QoS: SLO classes, fair admission, and priced isolation.

"Millions of users" means tenants with different contracts sharing one
fleet, and without this plane a single heavy tenant starves everyone:
admission was FIFO, pages were first-come, and any tenant's hedges
spent the whole fleet's slack (ROADMAP item 3). This package turns
tenancy into arithmetic the rest of the codebase consults:

* :mod:`.tenancy` — :class:`TenantContract` (SLO class ``latency`` |
  ``throughput`` | ``batch``, DRR ``weight``, token-rate budget with
  refill via :class:`TokenBucket`, KV page-pool quota, TTFT-hedge
  entitlement) and the :class:`TenantRegistry` every plane shares.
* :mod:`.drr` — :class:`DeficitScheduler`: weighted deficit-round-
  robin over per-tenant admission queues, work-conserving by
  construction (idle capacity always serves whoever is queued) with
  deficit counters that carry, so a starved tenant catches up
  *exactly*.

Consumers: :class:`~..models.serving.ServingScheduler` (``qos=``)
replaces FIFO admission with the DRR pick and enforces page quotas at
plan time with COW-aware cold-page reclaim;
:class:`~..models.router.RequestRouter` (``qos=``) charges token
buckets at submit (over-budget ``batch`` work is shed by name,
``outcome == "shed"``) and refuses hedges beyond a tenant's
entitlement; :class:`~..sim.workload.SimReplica` (``qos=``) runs the
identical DRR on virtual time so the isolation claim — a tenant
flooding 10x its budget moves compliant tenants' p99 TTFT by less
than a pinned epsilon while utilization stays above a floor — is
measured and replayed bit-identically (tests/test_qos.py,
benchmarks/qos_bench.py).

Wall-clock purity: graftcheck GC008 covers ``qos/`` like ``sim/`` and
``fleet/`` — nothing here reads an OS clock; buckets refill from the
``now`` the caller injects.
"""

from .drr import DeficitScheduler
from .tenancy import (
    SHED_ORDER,
    SLO_CLASSES,
    TenantContract,
    TenantRegistry,
    TokenBucket,
    shed_rank,
)

__all__ = [
    "SHED_ORDER",
    "SLO_CLASSES",
    "DeficitScheduler",
    "TenantContract",
    "TenantRegistry",
    "TokenBucket",
    "shed_rank",
]
