"""Weighted deficit-round-robin admission: fairness as arithmetic.

FIFO admission has one failure mode at fleet scale: a heavy tenant's
queue IS the queue, and everyone else's requests age behind it. The
classic fix (Shreedhar & Varghese's deficit round robin) keeps one
queue per tenant and serves them in a fixed rotation, each visit
granting the tenant a quantum of credit proportional to its contract
weight; a request is admitted when the tenant's accumulated credit
(its *deficit counter*) covers the request's cost. Two properties fall
out by construction, and both are what the QoS plane's tests pin:

* **work conservation** — the rotation only ever stops at a tenant
  with something queued, so idle capacity always serves whoever is
  waiting: an admission slot is never held empty in the name of
  fairness. A lone backlogged tenant receives everything.
* **exact catch-up** — deficit counters CARRY while a tenant stays
  backlogged: a tenant short-changed in one round (its head request
  cost more than its quantum) keeps the credit and is served first
  thereafter, so long-run shares converge to the weight ratio
  *exactly*, not asymptotically-in-expectation. (Credit does not
  survive IDLENESS — the standard DRR forfeit, applied here at the
  moment a tenant re-enters the rotation with fresh backlog:
  :meth:`~DeficitScheduler.enqueue` onto an empty queue zeroes the
  carry, so a burst can never cash in old idle time, while
  :meth:`~DeficitScheduler.restore` — which re-queues a PICKED item
  whose admission plan failed — bypasses the forfeit: a failed pick
  keeps its exact carry, the restored item IS the backlog.)

Cost is in TOKENS (prompt + budget — the same unit as the contracts'
rate budgets), so "fair" means fair chip work, not fair request
counts; with uniform requests the two coincide and a 2:1 weight ratio
admits exactly 2:1. The quantum unit is adaptive by default (the
largest cost seen so far), which keeps every pick O(#tenants): one
visit's grant always affords the head for weights >= 1.

Single-threaded by design (it lives inside a scheduler's tick loop),
pure in its inputs (no clock at all — graftcheck GC008 covers
``qos/``), and deterministic: rotation order is registration order,
never hash order, so a tenant-mixed day replays bit-identically.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Hashable, Iterable

from .tenancy import TenantRegistry

__all__ = ["DeficitScheduler"]


class DeficitScheduler:
    """Per-tenant admission queues under weighted DRR (module
    docstring for the algorithm and its guarantees).

    >>> drr = DeficitScheduler(registry)
    >>> drr.enqueue("acme", req, cost=160)
    >>> tenant, req, cost = drr.pick()     # the next admission
    >>> drr.restore(tenant, req, cost)     # plan failed: put it back

    ``pick(skip=...)`` returns the next ``(tenant, item, cost)`` per
    DRR order, dequeued and charged; tenants in ``skip`` are passed
    over without charge (the scheduler's per-pass deferral set — a
    tenant whose head cannot be planned right now must not block the
    rotation, which is exactly the head-of-line decoupling FIFO
    lacks). ``restore`` undoes one pick — the item returns to the
    FRONT of its queue and the cost is refunded — so a failed
    admission plan costs the tenant nothing."""

    def __init__(self, registry: TenantRegistry, *,
                 quantum_unit: float | None = None):
        self._registry = registry
        if quantum_unit is not None and not quantum_unit > 0:
            raise ValueError(
                f"quantum_unit must be > 0 or None (adaptive: the "
                f"largest cost seen), got {quantum_unit}"
            )
        self._unit = quantum_unit
        self._max_cost = 1.0  # adaptive-unit floor
        self._order: list[str] = []           # rotation = first-seen
        self._queues: dict[str, deque] = {}   # tenant -> (item, cost)
        self._deficit: dict[str, float] = {}
        self._cursor = 0
        self._granted = False  # current cursor already got its visit's quantum
        self._n = 0
        # causal-tracing hook (round 22, opt-in): the OWNER's callback
        # — ``hook(kind, tenant, item, cost)`` — fired at enqueue and
        # grant. The scheduler that owns this DRR owns the clock too;
        # qos/ itself stays clock-free (graftcheck GC008)
        self._trace_hook = None

    # -- introspection ---------------------------------------------------

    @property
    def total(self) -> int:
        """Queued items across every tenant."""
        return self._n

    def backlog(self, tenant: str) -> int:
        q = self._queues.get(tenant)
        return len(q) if q else 0

    def deficit(self, tenant: str) -> float:
        """The tenant's carried credit (tokens) — the catch-up state
        the exactness tests read and ``qos_deficit`` exports."""
        return self._deficit.get(tenant, 0.0)

    def backlogged(self, skip: Iterable[str] = ()) -> list[str]:
        s = set(skip)
        return [t for t in self._order
                if t not in s and self._queues.get(t)]

    def __bool__(self) -> bool:
        return self._n > 0

    def __iter__(self):
        """Queued items in rotation-then-queue order (cancel scans)."""
        for t in self._order:
            for item, _c in self._queues.get(t, ()):
                yield item

    # -- the queue faces -------------------------------------------------

    def set_trace(self, hook) -> None:
        """Install (or clear, with None) the owner's causal-tracing
        callback: ``hook(kind, tenant, item, cost)`` fires on
        ``drr_queued`` (enqueue) and ``drr_picked`` (grant). The hook
        stamps the owner's TraceBook on the OWNER's clock — this
        module never reads one."""
        self._trace_hook = hook

    def enqueue(self, tenant: str, item: Any, cost: float) -> None:
        """Queue ``item`` for ``tenant`` at ``cost`` tokens. The
        tenant must hold a contract (its weight is the quantum);
        unknown tenants are refused by name, never defaulted."""
        self._registry.get(tenant)  # raises the named KeyError
        if not cost > 0:
            raise ValueError(f"cost must be > 0 tokens, got {cost}")
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = deque()
            self._order.append(tenant)
        if not q:
            # fresh backlog after an idle period forfeits any banked
            # credit (module docstring); restore() deliberately does
            # not come through here
            self._deficit[tenant] = 0.0
        q.append((item, float(cost)))
        self._n += 1
        if cost > self._max_cost:
            self._max_cost = float(cost)
        if self._trace_hook is not None:
            self._trace_hook("drr_queued", tenant, item, float(cost))

    def _quantum(self, tenant: str) -> float:
        unit = self._unit if self._unit is not None else self._max_cost
        return self._registry.get(tenant).weight * unit

    def _advance(self) -> None:
        self._cursor = (self._cursor + 1) % max(len(self._order), 1)
        self._granted = False

    def pick(self, skip: Iterable[Hashable] = ()
             ) -> tuple[str, Any, float] | None:
        """Dequeue and charge the next admission per DRR order, or
        None when nothing outside ``skip`` is queued. One quantum is
        granted per visit (lazily — only when the carried deficit does
        not already cover the head), the visit ends when the next head
        is unaffordable, and credit never survives an idle period (a
        fresh enqueue onto an empty queue forfeits the carry — but a
        restore() never does; module docstring)."""
        s = set(skip)
        live = [t for t in self._order
                if t not in s and self._queues.get(t)]
        if not live:
            return None
        # termination: each full rotation grants every live tenant one
        # quantum, so the cheapest live head is affordable within
        # ceil(max_cost / min live quantum) rotations
        minq = min(self._quantum(t) for t in live)
        maxc = max(q[0][1] for t in live
                   for q in (self._queues[t],))
        limit = len(self._order) * (2 + int(maxc / minq))
        for _ in range(limit + 1):
            t = self._order[self._cursor]
            q = self._queues.get(t)
            if q and t not in s:
                item, c = q[0]
                d = self._deficit.get(t, 0.0)
                if d < c and not self._granted:
                    d = d + self._quantum(t)
                    self._deficit[t] = d
                    self._granted = True
                if d >= c:
                    q.popleft()
                    self._n -= 1
                    # the leftover CARRIES even when the queue empties
                    # — forfeiture happens at the next fresh enqueue
                    # (so restore() of a failed pick keeps the exact
                    # carry instead of silently losing it)
                    self._deficit[t] = d - c
                    if not q or self._deficit[t] < q[0][1]:
                        self._advance()
                    if self._trace_hook is not None:
                        self._trace_hook("drr_picked", t, item, c)
                    return t, item, c
            self._advance()
        raise AssertionError(
            "DRR rotation did not converge — quantum accounting bug"
        )

    def restore(self, tenant: str, item: Any, cost: float) -> None:
        """Undo one :meth:`pick`: the item returns to the FRONT of its
        tenant's queue and the charge is refunded — a failed admission
        plan (pool pressure, page quota) costs the tenant nothing and
        the next rotation retries it in place."""
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = deque()
            if tenant not in self._order:
                self._order.append(tenant)
        q.appendleft((item, float(cost)))
        self._n += 1
        self._deficit[tenant] = (
            self._deficit.get(tenant, 0.0) + float(cost)
        )

    def remove(self, item: Any) -> bool:
        """Withdraw a queued item wherever it sits (the cancel path).
        Identity comparison, like the schedulers' queue removal."""
        for t in self._order:
            q = self._queues.get(t)
            if not q:
                continue
            for pair in q:
                if pair[0] is item:
                    q.remove(pair)
                    self._n -= 1
                    # an emptied queue keeps its carry until the next
                    # fresh enqueue forfeits it (the enqueue rule)
                    return True
        return False

    def clear(self) -> None:
        """Drop every queue and every deficit (replica death)."""
        self._queues.clear()
        self._deficit.clear()
        self._order.clear()
        self._cursor = 0
        self._granted = False
        self._n = 0

    def __repr__(self) -> str:
        return (
            f"DeficitScheduler({self._n} queued over "
            f"{len(self.backlogged())} backlogged tenants)"
        )
