"""Gradient-coded training for ANY pytree model through the async pool.

The framework's two halves meet here. The pool half (pool.py — the
reference's fastest-k ``asyncmap`` contract, src/MPIAsyncPools.jl:68)
supplies straggler-tolerant dispatch with per-worker arrival masks; the
coding half (ops/gradcode.py, Tandon et al. cyclic gradient coding)
turns any ``n - s`` arrivals into the EXACT full-batch gradient; this
module lifts both from flat weight vectors (models/logreg.py, BASELINE
config 5) to arbitrary pytree models — the flagship transformer
included — via ``ravel_pytree``:

* the per-epoch payload is the raveled parameter vector (one flat
  device array — the minimal broadcast, and byte-compatible with every
  transport backend);
* worker ``i`` holds its ``s+1`` cyclic data chunks device-resident and
  runs ONE fused jitted program per epoch: unravel, per-chunk grads in
  a single vmap, coded linear combination, ravel — nothing but the flat
  coded gradient crosses the worker boundary;
* the coordinator decodes over whichever workers arrived
  (``pool.fresh_indices()`` is the ``repochs`` freshness mask of the
  reference contract) and applies the update — plain SGD or any optax
  transformation — on device.

Exactness is the point: training UNDER INJECTED STRAGGLERS follows the
bit-identical parameter trajectory of bulk-synchronous full-batch
training up to the decode's float dot — tests/test_coded_train.py pins
the transformer trajectory against direct full-batch SGD.
"""

from __future__ import annotations

import time
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from ..backends.base import DelayFn
from ..backends.xla import XLADeviceBackend
from ..pool import AsyncPool, asyncmap, waitall
from ..ops.gradcode import GradientCode
from .transformer import TransformerConfig, forward_dense

__all__ = ["CodedGradTrainer", "transformer_chunk_loss"]


class _TrainObs:
    """Instrument bundle for one trainer, resolved once at
    construction (the opt-in contract shared with the scheduler's
    ``_ServingObs`` and the pool tracer: a dark trainer's step pays
    only ``is not None`` checks)."""

    def __init__(self, trainer: "CodedGradTrainer", registry, spans):
        self.registry = registry
        self.spans = spans
        self._r = registry is not None
        if not self._r:
            return
        registry.gauge(
            "train_workers", help="pool size n of the gradient code"
        ).set(trainer.n)
        registry.gauge(
            "train_code_tolerance",
            help="stragglers s the cyclic code absorbs",
        ).set(trainer.s)
        self.m_steps = registry.counter("train_steps_total")
        self.m_step_s = registry.histogram(
            "train_step_seconds",
            help="asyncmap -> decode -> update wall clock",
        )
        self.m_fresh_k = registry.gauge(
            "train_decode_fresh_k",
            help="fresh arrivals the last decode recovered from",
        )
        self.m_stale = registry.counter(
            "train_stale_arrivals_total",
            help="stale pool arrivals (bridged from the EpochTracer)",
        )
        self.m_retask = registry.counter(
            "train_retasks_total",
            help="immediate re-dispatches (bridged from the EpochTracer)",
        )
        self.m_recovered = [
            registry.counter(
                "train_worker_recovered_total",
                help="steps whose decode consumed this worker's shard",
                worker=str(i),
            )
            for i in range(trainer.n)
        ]

    def step_done(
        self, trainer: "CodedGradTrainer", fresh, t0: float,
        epoch_rec,
    ) -> None:
        t1 = time.perf_counter()
        if self._r:
            self.m_steps.inc()
            self.m_step_s.observe(t1 - t0)
            self.m_fresh_k.set(len(fresh))
            for i in fresh:
                self.m_recovered[int(i)].inc()
            if epoch_rec is not None:
                self.m_stale.inc(epoch_rec.n_stale)
                self.m_retask.inc(epoch_rec.n_retask)
        if self.spans is not None:
            args = {"fresh_k": len(fresh)}
            if epoch_rec is not None:
                args["epoch"] = epoch_rec.epoch
                args["n_stale"] = epoch_rec.n_stale
                args["n_retask"] = epoch_rec.n_retask
            self.spans.add(
                f"coded step ({len(fresh)}/{trainer.n})", t0, t1 - t0,
                track="train", **args,
            )


def transformer_chunk_loss(cfg: TransformerConfig) -> Callable:
    """``loss(params, tokens)`` for :class:`CodedGradTrainer` chunks:
    next-token NLL of the dense transformer forward over a ``(B, L+1)``
    int token block (inputs ``[:, :-1]``, targets ``[:, 1:]``), in the
    same logsumexp form as the sharded path's ``nll_loss``."""

    def loss(params, tokens):
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        logits = forward_dense(params, inp, cfg).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
        return jnp.mean(lse - tl)

    return loss


class CodedGradTrainer:
    """Straggler-resilient exact-gradient training of a pytree model.

    ``loss_fn(params, batch) -> scalar`` defines the model;
    ``chunk_fn(j) -> batch`` yields global data chunk ``j`` (equal
    shapes across chunks — the full batch is the union of the ``n``
    chunks, and one training step optimizes the mean of the per-chunk
    losses). Worker ``i`` materializes chunks ``code.support(i)``
    device-resident at construction; epochs move only the flat params.

    >>> tr = CodedGradTrainer(loss, params0, chunk_fn, n_workers=8, s=2)
    >>> params, losses = tr.fit(epochs=20, lr=0.1)

    Pass ``tx`` (an optax ``GradientTransformation``) to replace plain
    SGD; the optimizer state lives coordinator-side and steps on the
    decoded exact gradient, so adaptive moments see the same gradient
    stream a bulk-synchronous run would.

    Observability (all opt-in, zero cost when omitted): ``tracer=`` (an
    :class:`~..utils.trace.EpochTracer`) threads through every
    ``asyncmap``/``waitall`` this trainer issues; ``registry=`` records
    per-step wall clock, which k-of-n workers each decode recovered
    from, and stale/re-task totals bridged from the tracer's epoch
    records; ``spans=`` (an :class:`~..obs.SpanRecorder`) draws one
    span per training step in the merged Perfetto timeline beside the
    tracer's worker spans.
    """

    def __init__(
        self,
        loss_fn: Callable,
        params0,
        chunk_fn: Callable[[int], object],
        n_workers: int,
        s: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        tx=None,
        seed: int = 0,
        tracer=None,
        registry=None,
        spans=None,
    ):
        if devices is None:
            devices = jax.devices()
        self.n, self.s = int(n_workers), int(s)
        self.code = GradientCode(self.n, self.s, seed=seed)
        self.tx = tx

        flat0, unravel = ravel_pytree(params0)
        flat0 = flat0.astype(jnp.float32)
        self._unravel = unravel
        self._flat0 = flat0

        def coded_grad(flat_w, stacked, coeffs):
            params = unravel(flat_w)

            def g(batch):
                return ravel_pytree(jax.grad(loss_fn)(params, batch))[0]

            G = jax.vmap(g)(stacked)  # (s+1, P)
            return coeffs @ G.astype(jnp.float32)

        self._coded_grad = jax.jit(coded_grad)
        self._loss_fn = loss_fn
        self._eval_loss = jax.jit(loss_fn)  # full_batch_loss is per-epoch

        # per-worker device-resident chunk stacks + code coefficients
        self._chunks = []
        for i in range(self.n):
            sup = self.code.support(i)
            dev = devices[i % len(devices)]
            stacked = jax.tree.map(
                lambda *xs: jax.device_put(jnp.stack(xs), dev),
                *[chunk_fn(j) for j in sup],
            )
            coeffs = jax.device_put(
                jnp.asarray(self.code.B[i, sup], jnp.float32), dev
            )
            self._chunks.append((stacked, coeffs))
        self.backend = XLADeviceBackend(
            self._work, self.n, devices=devices, delay_fn=delay_fn
        )
        self.tracer = tracer
        self.last_fresh: np.ndarray = np.array([], dtype=np.int64)
        self._obs = (
            _TrainObs(self, registry, spans)
            if registry is not None or spans is not None
            else None
        )

        if tx is not None:
            self.opt_state = tx.init(params0)

        def apply_sgd(flat_w, g_flat, lr):
            return flat_w - lr * g_flat

        self._apply_sgd = jax.jit(apply_sgd)

    def _work(self, i: int, flat_w: jax.Array, epoch: int) -> jax.Array:
        stacked, coeffs = self._chunks[i]
        return self._coded_grad(flat_w, stacked, coeffs)

    def _decode(self, pool: AsyncPool, dev) -> jax.Array:
        """Exact mean-of-chunks gradient from the arrived workers.
        Records the recovery set in ``last_fresh`` — which k-of-n
        workers this step's gradient actually came from."""
        fresh = pool.fresh_indices()
        self.last_fresh = fresh
        a = jnp.asarray(self.code.decode_weights(fresh), jnp.float32)
        G = jnp.stack([
            jax.device_put(jnp.asarray(pool.results[i]), dev)
            for i in fresh
        ])
        return (a @ G) / self.n

    def step(self, pool: AsyncPool, params, *, lr: float | None = None,
             epoch: int | None = None, nwait: int | None = None):
        """One coded step: asyncmap -> decode -> update. Returns the
        updated params pytree (device-resident). ``nwait`` defaults to
        the code's tolerance ``n - s``; pass ``n`` for a
        bulk-synchronous baseline epoch."""
        if nwait is None:
            nwait = self.n - self.s
        if (lr is None) == (self.tx is None):
            raise ValueError(
                "pass lr for plain SGD, or construct with tx= for optax "
                "(exactly one of the two)"
            )
        obs = self._obs
        t0 = time.perf_counter() if obs is not None else 0.0
        dev = self.backend.devices[0]
        flat_w, _ = ravel_pytree(params)
        flat_w = jax.device_put(flat_w.astype(jnp.float32), dev)
        asyncmap(pool, flat_w, self.backend, nwait=nwait, epoch=epoch,
                 tracer=self.tracer)
        g_flat = self._decode(pool, dev)
        if self.tx is None:
            out = self._unravel(self._apply_sgd(flat_w, g_flat, lr))
        else:
            g = self._unravel(g_flat)
            updates, self.opt_state = self.tx.update(
                g, self.opt_state, params
            )
            import optax

            out = optax.apply_updates(params, updates)
        if obs is not None:
            obs.step_done(
                self, self.last_fresh, t0,
                self.tracer.records[-1]
                if self.tracer is not None and self.tracer.records
                else None,
            )
        return out

    def full_batch_loss(self, params) -> float:
        """Mean per-chunk loss over all n chunks (each chunk counted
        once — worker 0's stack holds chunk 0 first, worker 1's chunk 1
        first, ...). Chunks are gathered to the coordinator device
        (worker chunks live on their own devices)."""
        dev = self.backend.devices[0]
        params = jax.device_put(params, dev)
        total = 0.0
        for i in range(self.n):
            stacked, _ = self._chunks[i]
            first = jax.tree.map(
                lambda x: jax.device_put(x[0], dev), stacked
            )
            total += float(self._eval_loss(params, first))
        return total / self.n

    def fit(self, epochs: int, params=None, *, lr: float | None = None,
            eval_every: int | None = 1):
        """Run coded training; returns (params, loss history). The
        history records :meth:`full_batch_loss` every ``eval_every``
        epochs (None disables evaluation)."""
        pool = AsyncPool(self.n)
        params = self._unravel(self._flat0) if params is None else params
        history = []
        for e in range(1, epochs + 1):
            params = self.step(pool, params, lr=lr)
            if eval_every is not None and e % eval_every == 0:
                history.append(self.full_batch_loss(params))
        # drain in-flight stragglers so the backend is reusable (traced:
        # the drains feed summary()'s waitall-aware straggler accounting)
        waitall(pool, self.backend, tracer=self.tracer)
        return params, history
