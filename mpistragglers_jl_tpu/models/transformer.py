"""Flagship model: decoder-only transformer, SPMD over a dp x sp x tp mesh.

The reference has no model code of any kind (SURVEY §2: "the library has
no model code at all") — its workloads are conventions written by users.
This framework ships model families as first-class components; the
transformer is the flagship long-context workload, exercising every
parallel mechanism the framework provides in one train step:

* **dp** — batch data parallelism: batch sharded over ``dp``; gradient
  averaging is the ``psum`` XLA inserts when the loss mean crosses the
  axis.
* **sp** — sequence/context parallelism: activations sharded over the
  sequence axis; attention is exact ring attention
  (parallel/ring_attention.py) whose K/V blocks ride ICI via
  ``ppermute``, or Ulysses all-to-all. This is the long-context story:
  per-device activation memory is O(L / sp).
* **tp** — Megatron-style tensor parallelism: attention heads and the
  MLP hidden dimension sharded over ``tp``; one ``psum`` after the
  attention out-projection and one after the MLP down-projection.
* **ep** — expert parallelism (``n_experts > 0``): the FFN becomes a
  top-1-routed mixture of experts (models/moe.py), experts sharded
  over ``ep``, the batch sharded over ``(dp, ep)``, token routing via
  one tiled ``all_to_all`` each way. Expert hidden dims additionally
  shard over ``tp``.

Pipeline parallelism over a ``pp`` axis is a separate program shape —
see parallel/pipeline.py and :func:`make_pipeline_train_step` there.

The whole train step is a single ``shard_map`` program under ``jit`` —
collectives are explicit where they are structural (ring ppermute, tp
psum) and compiler-inserted where they are incidental (loss mean). RoPE
positions are computed from the global offset ``sp_index * L_local``, so
sequence sharding is invisible to the math.

Weight layout (TPU-first): projections keep (d_model, heads, head_dim)
so the contracted dim is leading and heads*head_dim tile the MXU lanes;
everything defaults to float32 with a ``dtype`` knob for bfloat16
compute on real chips.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
from .. import _jax_compat  # noqa: F401  (installs older-JAX aliases)
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.ring_attention import (
    _flash_interpreted,
    resolve_attention_impl,
    ring_self_attention,
    ulysses_attention,
)
from .moe import (
    init_moe_layer,
    moe_ffn_dense,
    moe_ffn_sharded,
    moe_layer_specs,
)

__all__ = [
    "TransformerConfig",
    "init_params",
    "param_specs",
    "forward_dense",
    "make_forward",
    "make_train_step",
    "make_optax_train_step",
    "optax_step",
    "shard_params",
    "batch_axes",
    "data_spec",
]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    # grouped-query attention: number of K/V heads; None = n_heads (MHA),
    # 1 = MQA. Q heads h and h+1.. share kv head h // (n_heads //
    # n_kv_heads) — the grouping every kernel (reference, flash, ring,
    # Ulysses) implements natively, so K/V projections, the KV cache and
    # the ring/all_to_all K/V traffic all shrink by the group factor.
    n_kv_heads: int | None = None
    n_layers: int = 2
    d_ff: int = 256
    attn: str = "ring"  # "ring" | "ulysses" | used inside shard_map
    # per-device attention kernel: "reference" (materializing oracle) or
    # "flash" (fused Pallas kernel, ops/flash_attention.py) — applies to
    # the dense forward and to the local attention inside Ulysses
    attn_impl: str = "reference"
    # sliding-window attention (Mistral-style): each position attends
    # the previous `attn_window` positions only (None = full causal).
    # Flows through every kernel — the reference oracle, the flash
    # kernels (which SKIP blocks left of the band), ring, Ulysses —
    # and the KV-cache decode path masks the same band.
    attn_window: int | None = None
    # n_experts > 0 replaces every layer's dense MLP with a top-1-routed
    # MoE (models/moe.py) whose experts shard over an "ep" mesh axis
    n_experts: int = 0
    capacity_factor: float = 2.0
    # Switch load-balance aux-loss weight; 0 keeps the sharded loss
    # bit-identical to the dense oracle (local vs global token means
    # differ), nonzero is what real training wants
    moe_aux_coef: float = 0.0
    # remat=True wraps every transformer layer in jax.checkpoint: the
    # backward recomputes the layer's activations instead of keeping
    # them resident — the standard FLOPs-for-HBM trade for long
    # sequences / deep stacks. Same math: loss matches exactly and
    # gradients to float tolerance (rtol 1e-6, since the recomputed
    # backward may fuse/order differently — tests/test_transformer.py).
    remat: bool = False
    dtype: Any = jnp.float32

    def __post_init__(self):
        if self.attn == "ring" and self.attn_impl == "flash":
            # ring attention accumulates block-wise itself; flash only
            # applies to the per-device full-sequence attention (dense
            # forward / inside Ulysses). Accepting the combination would
            # silently run ring without flash while the dense oracle
            # diverged to a different kernel.
            raise ValueError(
                'attn_impl="flash" requires attn="ulysses" (ring '
                "attention has no per-device full-sequence kernel)"
            )
        if self.d_model % self.n_heads != 0:
            raise ValueError(
                f"d_model {self.d_model} not divisible by n_heads "
                f"{self.n_heads}"
            )
        if (self.d_model // self.n_heads) % 2 != 0:
            raise ValueError(
                f"RoPE requires even head_dim, got "
                f"{self.d_model // self.n_heads}"
            )
        if self.attn_window is not None and self.attn_window < 1:
            raise ValueError(
                f"attn_window must be >= 1, got {self.attn_window}"
            )
        if self.n_kv_heads is not None and (
            self.n_kv_heads < 1 or self.n_heads % self.n_kv_heads != 0
        ):
            raise ValueError(
                f"n_kv_heads {self.n_kv_heads} must divide n_heads "
                f"{self.n_heads}"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        """Resolved K/V head count (n_heads when n_kv_heads is None)."""
        return self.n_heads if self.n_kv_heads is None else self.n_kv_heads


def init_params(cfg: TransformerConfig, seed: int = 0) -> dict:
    """Plain pytree-of-arrays parameters (replicable / shardable)."""
    rng = np.random.default_rng(seed)
    D, H, Dh, F = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    Hkv = cfg.kv_heads
    sd = lambda *s: jnp.asarray(
        rng.standard_normal(s) / np.sqrt(s[0]), cfg.dtype
    )
    layers = []
    for _ in range(cfg.n_layers):
        layer = {
            "ln1_s": jnp.ones((D,), cfg.dtype),
            "ln1_b": jnp.zeros((D,), cfg.dtype),
            "wq": sd(D, H, Dh),
            "wk": sd(D, Hkv, Dh),
            "wv": sd(D, Hkv, Dh),
            # NB float(): an np.float64 scalar would silently promote
            # the param to f64 under jax_enable_x64
            "wo": sd(H, Dh, D) / float(np.sqrt(cfg.n_layers)),
            "ln2_s": jnp.ones((D,), cfg.dtype),
            "ln2_b": jnp.zeros((D,), cfg.dtype),
        }
        if cfg.n_experts:
            layer.update(
                init_moe_layer(
                    rng, D, F, cfg.n_experts, cfg.n_layers, cfg.dtype
                )
            )
        else:
            layer.update(
                {
                    "w1": sd(D, F),
                    "b1": jnp.zeros((F,), cfg.dtype),
                    "w2": sd(F, D) / float(np.sqrt(cfg.n_layers)),
                    "b2": jnp.zeros((D,), cfg.dtype),
                }
            )
        layers.append(layer)
    return {
        "emb": jnp.asarray(
            rng.standard_normal((cfg.vocab, D)) * 0.02, cfg.dtype
        ),
        "layers": layers,
        "lnf_s": jnp.ones((D,), cfg.dtype),
        "lnf_b": jnp.zeros((D,), cfg.dtype),
    }


def _kv_tp_sharded(cfg: TransformerConfig, mesh: Mesh | None) -> bool:
    """Whether the K/V projections shard their (narrower) head dim over
    ``tp``. With GQA/MQA the kv-head count can drop below the tp degree;
    then wk/wv stay replicated and each tp member slices the one kv head
    its q-head shard reads (``_forward_local``). Requires kv_heads % tp
    == 0 or tp % kv_heads == 0 — anything else has no aligned grouping."""
    if mesh is None or "tp" not in mesh.axis_names:
        return True
    tp = mesh.shape["tp"]
    if cfg.n_heads % tp != 0:
        raise ValueError(
            f"n_heads {cfg.n_heads} must divide over tp={tp}"
        )
    if cfg.kv_heads % tp == 0:
        return True
    if tp % cfg.kv_heads == 0:
        return False
    raise ValueError(
        f"kv_heads {cfg.kv_heads} and tp={tp} need one to divide the "
        "other (grouped q-head shards must align to whole kv heads)"
    )


def param_specs(cfg: TransformerConfig, mesh: Mesh | None = None) -> dict:
    """PartitionSpecs matching :func:`init_params`: heads and d_ff over
    ``tp`` (Megatron split), everything else replicated. Pass ``mesh``
    so GQA configs whose kv_heads < tp degree fall back to replicated
    K/V projections (see :func:`_kv_tp_sharded`)."""
    kv = P(None, "tp", None) if _kv_tp_sharded(cfg, mesh) else P()
    layer = {
        "ln1_s": P(), "ln1_b": P(),
        "wq": P(None, "tp", None),
        "wk": kv,
        "wv": kv,
        "wo": P("tp", None, None),
        "ln2_s": P(), "ln2_b": P(),
    }
    if cfg.n_experts:
        layer.update(moe_layer_specs())
    else:
        layer.update(
            {
                "w1": P(None, "tp"),
                "b1": P("tp"),
                "w2": P("tp", None),
                "b2": P(),
            }
        )
    return {
        "emb": P(),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "lnf_s": P(),
        "lnf_b": P(),
    }


def _ln(x, s, b, eps=1e-5):
    x = x.astype(jnp.float32)
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return ((x - mu) * jax.lax.rsqrt(var + eps)).astype(s.dtype) * s + b


def _rope(x, pos):
    """Rotary embedding; pos carries GLOBAL token positions (L,)."""
    B, L, H, Dh = x.shape
    half = Dh // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]  # (L, half)
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )


def _attn_block(x, lp, pos, attn_fn, kv_slice=None):
    """Attention half-block on (B, L?, D) activations; the head dim may
    be the tp-local shard — the caller supplies matching weights and the
    tp psum when sharded (``attn_fn`` closes over sp specifics).
    ``kv_slice`` post-selects kv heads from tp-replicated K/V
    projections (the GQA kv_heads < tp case — see
    :func:`_kv_tp_sharded`)."""
    h = _ln(x, lp["ln1_s"], lp["ln1_b"])
    q = jnp.einsum("bld,dhk->blhk", h, lp["wq"])
    k = jnp.einsum("bld,dhk->blhk", h, lp["wk"])
    v = jnp.einsum("bld,dhk->blhk", h, lp["wv"])
    if kv_slice is not None:
        k, v = kv_slice(k), kv_slice(v)
    q, k = _rope(q, pos), _rope(k, pos)
    o = attn_fn(q, k, v)
    return jnp.einsum("blhk,hkd->bld", o, lp["wo"])


def _mlp(x, lp):
    a = jax.nn.gelu(jnp.einsum("bld,df->blf", x, lp["w1"]) + lp["b1"])
    return jnp.einsum("blf,fd->bld", a, lp["w2"])


def make_kv_slice(cfg: TransformerConfig):
    """GQA with kv_heads < tp (call inside shard_map): wk/wv arrive
    tp-REPLICATED (:func:`_kv_tp_sharded`); this device's q-head shard
    [t*H/tp, (t+1)*H/tp) reads exactly one kv head, t*kv_heads // tp —
    the returned callable slices it so the attention kernels see the
    aligned local grouping (all local q heads -> local kv head 0).
    Returns None when kv heads shard evenly (nothing to slice). Shared
    by the training forward and the decode path (models/decode.py) so
    the index math cannot drift between them."""
    tp = jax.lax.axis_size("tp")
    if cfg.kv_heads % tp == 0:
        return None

    def kv_slice(a):
        idx = jax.lax.axis_index("tp") * cfg.kv_heads // tp
        return jax.lax.dynamic_slice_in_dim(a, idx, 1, axis=2)

    return kv_slice


def _local_attention(cfg: TransformerConfig):
    """The per-device (unsharded) attention kernel selected by config."""
    return partial(
        resolve_attention_impl(cfg.attn_impl), causal=True,
        window=cfg.attn_window,
    )


def forward_dense(params: dict, tokens: jax.Array, cfg: TransformerConfig):
    """Unsharded oracle forward: full attention, no collectives. The
    sharded program must agree with this bit-for-float."""
    return _forward_dense_aux(params, tokens, cfg)[0]


def _forward_dense_aux(params, tokens, cfg: TransformerConfig):
    """Dense forward returning (logits, summed MoE aux loss)."""
    pos = jnp.arange(tokens.shape[1])
    x = params["emb"][tokens]
    attn_fn = _local_attention(cfg)

    def one_layer(x, lp):
        attn_out = _attn_block(x, lp, pos, attn_fn)
        x = x + attn_out
        h = _ln(x, lp["ln2_s"], lp["ln2_b"])
        if cfg.n_experts:
            y, a = moe_ffn_dense(h, lp, cfg.capacity_factor)
            return x + y, a
        return x + _mlp(h, lp) + lp["b2"], jnp.float32(0.0)

    layer_fn = jax.checkpoint(one_layer) if cfg.remat else one_layer
    aux = jnp.float32(0.0)
    for lp in params["layers"]:
        x, a = layer_fn(x, lp)
        aux = aux + a
    x = _ln(x, params["lnf_s"], params["lnf_b"])
    return jnp.einsum("bld,vd->blv", x, params["emb"]), aux  # tied head


def _forward_local(params, tokens, cfg: TransformerConfig):
    """Per-shard forward: tokens are the batch/sequence-local chunk,
    params the tp/ep-local shards. Returns (local logits (B', L', V),
    summed MoE aux loss)."""
    Lc = tokens.shape[1]
    pos = jax.lax.axis_index("sp") * Lc + jnp.arange(Lc)
    if cfg.attn == "ring":
        attn = partial(
            ring_self_attention, axis="sp", causal=True,
            window=cfg.attn_window,
        )
    elif cfg.attn == "ulysses":
        attn = partial(
            ulysses_attention, axis="sp", causal=True,
            impl=cfg.attn_impl, window=cfg.attn_window,
        )
    else:
        raise ValueError(f"unknown sharded attention kind {cfg.attn!r}")
    kv_slice = make_kv_slice(cfg)
    x = params["emb"][tokens]

    def one_layer(x, lp):
        attn_out = _attn_block(x, lp, pos, attn, kv_slice)
        # tp combine: heads were a shard, the out-projection partial-sums
        attn_out = jax.lax.psum(attn_out, "tp")
        x = x + attn_out
        h = _ln(x, lp["ln2_s"], lp["ln2_b"])
        if cfg.n_experts:
            y, ybias, a = moe_ffn_sharded(h, lp, cfg.capacity_factor)
            # expert hidden dims are tp shards; bias rides outside the
            # psum (it is tp-replicated, see moe_ffn_sharded)
            return x + jax.lax.psum(y, "tp") + ybias, a
        y = jax.lax.psum(_mlp(h, lp), "tp")  # d_ff shard partial-sum
        return x + y + lp["b2"], jnp.float32(0.0)  # b2 replicated

    # remat recomputes each layer's activations in the backward — the
    # collectives inside (tp psum, ring ppermute / ulysses all_to_all,
    # MoE all_to_all) replay under jax.checkpoint like any other op
    layer_fn = jax.checkpoint(one_layer) if cfg.remat else one_layer
    aux = jnp.float32(0.0)
    for lp in params["layers"]:
        x, a = layer_fn(x, lp)
        aux = aux + a
    x = _ln(x, params["lnf_s"], params["lnf_b"])
    return jnp.einsum("bld,vd->blv", x, params["emb"]), aux


def batch_axes(cfg: TransformerConfig) -> tuple[str, ...]:
    """Mesh axes the batch/sequence is sharded over: MoE adds ``ep`` as
    an extra batch-sharding axis so every ep member routes distinct
    tokens (GShard layout)."""
    return ("dp", "ep", "sp") if cfg.n_experts else ("dp", "sp")


def data_spec(cfg: TransformerConfig) -> P:
    """PartitionSpec of global (B, L) token arrays."""
    return P(("dp", "ep"), "sp") if cfg.n_experts else P("dp", "sp")


def nll_loss(logits, targets, axes):
    """Mean token NLL over all devices of the batch-sharding ``axes``;
    call inside shard_map (shared by the flat and pipeline programs).

    Written in logsumexp form (``lse - logits[target]``) rather than
    ``log_softmax`` + gather: same math, same gradient (softmax minus
    one-hot), but the full (B, L, V) normalized array is never
    materialized in f32 — only the reductions are. On the chip that is
    10.5 ms of a 116 ms flagship step (measured round 4, docs/PERF.md
    phase table: the head+loss phase drops 22.5 -> 12.0 ms)."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = lse - tl
    total = jax.lax.psum(nll.sum(), axes)
    count = jax.lax.psum(jnp.asarray(nll.size, jnp.float32), axes)
    return total / count


def sgd_step(loss_fn, *, lr: float, donate: bool = False):
    """Jitted (params, tokens, targets) -> (params, loss) SGD step over
    any shard_map loss; XLA propagates the NamedShardings through the
    update (shared by the flat and pipeline train steps).

    ``donate=True`` donates the incoming params to the update so XLA
    writes the new params into the same HBM buffers — the layout for
    iterated training loops (the bench chains steps this way); the
    caller must not reuse a donated pytree after the call."""
    return sgd_step_from_grads(
        _value_and_grad3(loss_fn), lr=lr, donate=donate
    )


def _loss_local(params, tokens, targets, cfg: TransformerConfig):
    logits, aux = _forward_local(params, tokens, cfg)
    axes = batch_axes(cfg)
    loss = nll_loss(logits, targets, axes)
    if cfg.n_experts and cfg.moe_aux_coef:
        # mean of the per-member aux losses (each over local tokens)
        members = jax.lax.psum(jnp.float32(1.0), axes)
        loss = loss + cfg.moe_aux_coef * jax.lax.psum(aux, axes) / members
    return loss


def make_forward(cfg: TransformerConfig, mesh: Mesh):
    """Jitted sharded forward over global (B, L) token arrays."""

    def fwd_local(params, tokens):
        return _forward_local(params, tokens, cfg)[0]

    f = jax.shard_map(
        fwd_local,
        mesh=mesh,
        in_specs=(param_specs(cfg, mesh), data_spec(cfg)),
        out_specs=data_spec(cfg),
        # interpret-mode Pallas (flash attn on the CPU test mesh) trips
        # the vma checker — see parallel/ring_attention._make_wrapped;
        # compiled-on-TPU flash keeps the check on
        check_vma=not _flash_interpreted(cfg.attn_impl),
    )
    return jax.jit(f)


def optax_step(loss_fn, tx, *, donate: bool = False):
    """Jitted (params, opt_state, tokens, targets) -> (params,
    opt_state, loss) step for any optax GradientTransformation over a
    shard_map loss. Build the optimizer state with
    :func:`make_opt_init`'s ``init_state`` — NOT bare
    ``jax.jit(tx.init)``, which does not propagate the params'
    shardings to the moments (see :func:`make_opt_init`).
    ``donate=True`` donates params AND opt_state for in-place HBM
    updates in iterated loops."""
    return optax_step_from_grads(
        _value_and_grad3(loss_fn), tx, donate=donate
    )


def _value_and_grad3(loss_fn):
    def grad_fn(params, tokens, targets):
        return jax.value_and_grad(loss_fn)(params, tokens, targets)

    return grad_fn


def sgd_step_from_grads(grad_fn, *, lr: float, donate: bool = False):
    """SGD update over any ``grad_fn(params, tokens, targets) ->
    (loss, grads)`` — the shared body of :func:`sgd_step` and the
    pipeline train steps (parallel/pipeline.py), so the update rule
    lives in exactly one place."""

    def step(params, tokens, targets):
        loss, grads = grad_fn(params, tokens, targets)
        params = jax.tree.map(
            lambda p, g: p - lr * g.astype(p.dtype), params, grads
        )
        return params, loss

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def optax_step_from_grads(grad_fn, tx, *, donate: bool = False):
    """Optax update over any ``grad_fn(params, tokens, targets) ->
    (loss, grads)`` (shared by :func:`optax_step` and the pipeline
    optax step)."""
    import optax

    def step(params, opt_state, tokens, targets):
        loss, grads = grad_fn(params, tokens, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def _make_loss_fn(cfg: TransformerConfig, mesh: Mesh):
    """The sharded scalar loss both train-step flavors differentiate
    (one place for the spec wiring and the interpreted-flash vma
    exemption — see make_forward)."""
    return jax.shard_map(
        partial(_loss_local, cfg=cfg),
        mesh=mesh,
        in_specs=(param_specs(cfg, mesh), data_spec(cfg), data_spec(cfg)),
        out_specs=P(),
        check_vma=not _flash_interpreted(cfg.attn_impl),
    )


def make_optax_train_step(
    cfg: TransformerConfig, mesh: Mesh, tx, *, donate: bool = False,
):
    """Like :func:`make_train_step` but stepping any optax optimizer
    (Adam/AdamW/etc.) instead of plain SGD. Returns ``(step,
    init_state)``; calling ``init_state(params)`` builds the optimizer
    state under jit so every state leaf inherits its param's
    NamedSharding (tp-sharded weights get tp-sharded moments — no
    replicated extra model copies in HBM):

    >>> tx = optax.adamw(3e-4)
    >>> step, init_state = make_optax_train_step(cfg, mesh, tx)
    >>> opt_state = init_state(params)
    >>> params, opt_state, loss = step(params, opt_state, inp, tgt)

    The reference has no optimizer layer at all (its workloads are
    user conventions); this is framework surface the flagship model
    family needs.
    """
    step = optax_step(_make_loss_fn(cfg, mesh), tx, donate=donate)
    return step, make_opt_init(tx)


def make_opt_init(tx):
    """(params) -> optimizer state whose param-like leaves (moments)
    carry their parameter's sharding FROM INIT, not only after the
    first step. ``jax.jit(tx.init)`` alone does NOT propagate input
    shardings to its outputs (measured: every moment lands
    single-device; the round-3 assertion only passed because it ran
    after a step had resharded the state). The state's sharding pytree
    is built up front (param-like leaves take their parameter's
    sharding via ``optax.tree_map_params`` over an ``eval_shape``
    skeleton, step counts replicate) and passed as jit
    ``out_shardings`` — so the state MATERIALIZES sharded and no
    unsharded copy ever exists, which matters at exactly the scale
    where sharded moments are the point."""
    import optax

    def init_state(params):
        shardings = [
            p.sharding for p in jax.tree.leaves(params)
            if isinstance(p, jax.Array)
            and isinstance(p.sharding, NamedSharding)
        ]
        if not shardings:
            return jax.jit(tx.init)(params)  # dense/single-device
        replicated = NamedSharding(shardings[0].mesh, P())
        skeleton = jax.eval_shape(tx.init, params)
        out_shardings = optax.tree_map_params(
            tx,
            lambda _, p: p.sharding,
            skeleton,
            params,
            transform_non_params=lambda _: replicated,
        )
        return jax.jit(tx.init, out_shardings=out_shardings)(params)

    return init_state


def make_train_step(
    cfg: TransformerConfig, mesh: Mesh, *, lr: float = 1e-2,
    donate: bool = False,
):
    """Jitted (params, tokens, targets) -> (params, loss) SGD step.

    The loss/grad runs as one shard_map program (explicit ring/tp
    collectives inside); the parameter update stays in plain jit where
    XLA propagates the NamedShardings.
    """
    return sgd_step(_make_loss_fn(cfg, mesh), lr=lr, donate=donate)


def shard_params(params: dict, cfg: TransformerConfig, mesh: Mesh) -> dict:
    """Place a replicated param pytree onto the mesh per param_specs."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        param_specs(cfg, mesh),
    )
