"""Speculative decoding with n-gram (prompt-lookup) drafting.

Serving-side throughput for the flagship decode path (models/decode.py):
instead of one forward per token, draft ``k`` candidate tokens by
bigram lookup in the already-generated context, verify all of them in
ONE ``k+1``-token forward against the KV cache (the chunked-extend
program shape), and accept the longest matching prefix plus the
model's own correction token. Every iteration emits between 1 and
``k+1`` tokens.

**The output is exactly the greedy stream** — speculation is a
scheduling transform, not an approximation: a draft token is accepted
only when it equals the argmax the model produces at that position
teacher-forced on the exact accepted prefix, and the first rejected
position emits that argmax instead. tests/test_speculative.py pins
token-for-token equality with ``generate_dense`` on random, repetitive,
and adversarial prompts; the speedup is the only thing that varies
(acceptance depends on how self-predictable the stream is — lookup
drafting wins on loops, templates, and copy-heavy continuations).

Cache-consistency argument (why rejected drafts never poison the KV
cache): the verify forward at cursor ``c`` writes positions
``[c-1, c+k-1]`` *before* attending (``_incremental_layer`` updates
then reads). After accepting ``m+1`` tokens the next verify starts at
``c' = c+m+1 <= c+k+1``, so its write window ``[c'-1, c'+k-1]`` covers
every stale position ``[c', c+k-1]`` left by the rejected tail —
garbage is always overwritten before any read reaches it.

The draft itself is device-side (no host round trips): find the most
recent earlier occurrence of the current bigram and propose the ``k``
tokens that followed it; with no match, repeat the last token (any
draft is CORRECT — a bad one just lowers acceptance).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .decode import (
    _check_decode_mesh,
    _incremental_forward,
    init_cache,
    prefill_dense,
)
from .transformer import TransformerConfig

__all__ = [
    "generate_speculative_dense",
    "make_speculative_dense",
    "make_speculative",
]


def _bigram_draft(buf, cursor, k: int):
    """(L,) token buffer, known through ``cursor`` -> (k,) draft.

    Proposes the continuation of the most recent earlier occurrence of
    the last known bigram ``(buf[cursor-2], buf[cursor-1])``; falls
    back to repeating the last token. Pure device ops, O(L) compare."""
    L = buf.shape[0]
    idx = jnp.arange(L)
    a0, a1 = buf[cursor - 2], buf[cursor - 1]
    nxt = jnp.roll(buf, -1)
    match = (buf == a0) & (nxt == a1) & (idx < cursor - 2)
    p = jnp.max(jnp.where(match, idx, -1))
    has = p >= 0
    start = jnp.where(has, p + 2, cursor - 1)
    dr = jax.lax.dynamic_slice(buf, (start,), (k,))
    return jnp.where(has, dr, buf[cursor - 1])


def _spec_loop(prefill, step, cache, prompt, Tp: int, n_new: int,
               k: int):
    """THE draft/verify loop — the exact-greedy acceptance contract
    lives here once, shared by the dense and sharded programs.

    ``prefill(prompt, cache) -> (logits (1, Tp, V), cache)``;
    ``step(chunk (1, k+1), cache, offset) -> (logits, cache)``.
    Returns the packed ``(n_new + 1,)`` array: tokens + the verify-
    forward count in the last slot (one array = one D2H fetch — two
    separate fetches cost two tunnel round trips, the difference
    between a measured win and a measured loss on the bench chip)."""
    if prompt.shape[1] != Tp:
        raise ValueError(
            f"program compiled for Tp={Tp}, got prompt of "
            f"{prompt.shape[1]} tokens: positions past the prompt "
            "would attend unwritten zero K/V and diverge silently"
        )
    Lbuf = Tp + n_new + k + 1  # slack: the last verify may overrun
    logits, cache = prefill(prompt, cache)
    first = jnp.argmax(logits[0, -1]).astype(prompt.dtype)
    buf = jnp.zeros((Lbuf,), prompt.dtype)
    buf = jax.lax.dynamic_update_slice(buf, prompt[0], (0,))
    buf = buf.at[Tp].set(first)

    def cond(state):
        _, cursor, _, _ = state
        return cursor < Tp + n_new

    def body(state):
        buf, cursor, cache, iters = state
        draft = _bigram_draft(buf, cursor, k)  # (k,)
        chunk = jnp.concatenate(
            [jax.lax.dynamic_slice(buf, (cursor - 1,), (1,)), draft]
        )[None]  # (1, k+1) at positions cursor-1 .. cursor+k-1
        lg, cache = step(chunk, cache, cursor - 1)
        greedy = jnp.argmax(lg[0], axis=-1).astype(buf.dtype)  # (k+1,)
        # greedy[i] is the model's token for position cursor+i given
        # the exact prefix; accept drafts while they match it
        acc = jnp.cumprod((greedy[:k] == draft).astype(jnp.int32))
        m = jnp.sum(acc, dtype=jnp.int32)  # accepted drafts, 0..k
        draft_ext = jnp.concatenate([draft, draft[-1:]])
        # emit[i<m] = draft[i] (== greedy[i]); emit[m] = greedy[m]
        # (the correction); entries past m are dead — overwritten
        # by later iterations before any read
        emit = jnp.where(jnp.arange(k + 1) < m, draft_ext, greedy)
        buf = jax.lax.dynamic_update_slice(buf, emit, (cursor,))
        return buf, cursor + m + 1, cache, iters + 1

    buf, cursor, _, iters = jax.lax.while_loop(
        cond, body, (buf, jnp.int32(Tp + 1), cache, jnp.int32(0))
    )
    return jnp.concatenate(
        [buf[Tp:Tp + n_new], iters.astype(buf.dtype)[None]]
    )


@functools.lru_cache(maxsize=64)
def _spec_runner(cfg: TransformerConfig, Tp: int, n_new: int, k: int):
    Lbuf = Tp + n_new + k + 1

    @jax.jit
    def run(params, prompt):
        cache = init_cache(cfg, 1, Lbuf)
        return _spec_loop(
            lambda pr, c: prefill_dense(params, pr, c, cfg),
            lambda ch, c, off: _incremental_forward(
                params, ch, c, off, cfg, prefill=False
            ),
            cache, prompt, Tp, n_new, k,
        )

    return run


def make_speculative_dense(
    cfg: TransformerConfig, Tp: int, n_new: int, k: int = 4,
):
    """The raw jitted program: ``run(params, prompt (1, Tp)) ->
    (n_new + 1,) device array`` of tokens plus the verify-forward count
    in the last slot (one array = one D2H fetch). For callers that
    manage fencing themselves (benchmarks chaining several generations
    per fence); everyone else wants
    :func:`generate_speculative_dense`."""
    return _spec_runner(cfg, int(Tp), int(n_new), int(k))


def generate_speculative_dense(
    params, prompt, n_new: int, cfg: TransformerConfig, *, k: int = 4,
):
    """Greedy generation via draft-k/verify-in-one-forward speculation.

    ``prompt``: (1, Tp) int tokens, Tp >= 2 (the bigram draft needs
    one). Returns ``(tokens (1, n_new), n_forwards)`` — the token
    stream is EXACTLY ``generate_dense``'s greedy stream; the decode
    forward count is what speculation buys: ``1 + n_forwards`` total
    model calls (prefill + verifies) instead of ``1 + (n_new - 1)``,
    i.e. ``(n_new - 1) / n_forwards`` tokens per decode forward (> 1
    whenever drafts are being accepted; each verify forward is k+1
    tokens wide, so the FLOPs per forward rise — the win is real when
    decode is bandwidth/latency-bound, which is what the cache reads
    make it). Greedy only (sampling breaks the exact-equality
    contract this implementation pins)."""
    B, Tp = prompt.shape
    if B != 1:
        raise ValueError(
            f"speculative decode is per-stream (B=1), got batch {B}; "
            "vmap/shard the stream level instead"
        )
    if Tp < 2:
        raise ValueError(f"bigram drafting needs a prompt >= 2, got {Tp}")
    if n_new < 1:
        raise ValueError(f"n_new must be >= 1, got {n_new}")
    if k < 1:
        raise ValueError(f"draft length k must be >= 1, got {k}")
    packed = np.asarray(
        _spec_runner(cfg, Tp, n_new, int(k))(params, prompt)
    )
    return packed[None, :n_new], int(packed[n_new])


def make_speculative(cfg: TransformerConfig, mesh, Tp: int, n_new: int,
                     *, k: int = 4):
    """Sharded speculative generation over a (dp=1, tp) mesh:
    ``run(params, prompt (1, Tp)) -> (n_new + 1,)`` packed tokens +
    forward count, same contract as :func:`make_speculative_dense`.

    The draft/verify while_loop (``_spec_loop`` — shared with the
    dense program, so the exact-greedy acceptance logic lives once)
    runs inside ONE shard_map jit: every tp member computes identical
    post-psum logits, hence the identical argmax, draft, and
    acceptance — the speculation control flow replicates for free,
    exactly like greedy ``make_generate``'s token picks. Per-stream
    (B=1): speculation is a latency optimization for one sequence;
    shard extra streams over dp by running one program per stream.
    Dense configs only: the MoE all_to_all marks the loop carries
    varying over ep, which the replicated-control-flow scheme cannot
    express — MoE serving uses :func:`~.decode.make_generate`."""
    from jax.sharding import PartitionSpec as P

    from .decode import (
        _cache_heads_global,
        _zero_cache_layer,
        make_kv_slice,
    )
    from .transformer import param_specs

    _check_decode_mesh(cfg, mesh)
    if cfg.n_experts:
        raise ValueError(
            "sharded speculative decoding supports dense configs only "
            "(MoE expert-parallel carries cannot replicate across the "
            "speculation loop); serve MoE with make_generate"
        )
    if int(mesh.shape["dp"]) != 1:
        raise ValueError(
            "speculative decode is per-stream: use dp=1 (run one "
            "program per stream for batch serving)"
        )
    if Tp < 2 or n_new < 1 or k < 1:
        raise ValueError(f"need Tp >= 2, n_new >= 1, k >= 1; got "
                         f"{(Tp, n_new, k)}")
    Lbuf = Tp + n_new + k + 1

    def local(params, prompt):
        kv_slice = make_kv_slice(cfg)
        Hc = _cache_heads_global(cfg, mesh)
        tp = mesh.shape["tp"]
        cache = [
            _zero_cache_layer(1, Lbuf, Hc // tp, cfg.head_dim,
                              cfg.dtype, False)
            for _ in range(cfg.n_layers)
        ]
        return _spec_loop(
            lambda pr, c: _incremental_forward(
                params, pr, c, jnp.int32(0), cfg, prefill=True,
                kv_slice=kv_slice, tp_psum=True,
            ),
            lambda ch, c, off: _incremental_forward(
                params, ch, c, off, cfg, prefill=False,
                kv_slice=kv_slice, tp_psum=True,
            ),
            cache, prompt, Tp, n_new, k,
        )

    # prompt replicated (dp=1 enforced above): every member runs the
    # identical control flow on identical post-psum logits, so the
    # packed output is unvarying on every mesh axis
    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs(cfg, mesh), P()),
        out_specs=P(),
    )
    return jax.jit(f)
