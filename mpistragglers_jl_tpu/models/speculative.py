"""Speculative decoding with n-gram (prompt-lookup) drafting.

Serving-side throughput for the flagship decode path (models/decode.py):
instead of one forward per token, draft ``k`` candidate tokens by
bigram lookup in the already-generated context, verify all of them in
ONE ``k+1``-token forward against the KV cache (the chunked-extend
program shape), and accept the longest matching prefix plus the
model's own correction token. Every iteration emits between 1 and
``k+1`` tokens.

**The output is exactly the greedy stream** — speculation is a
scheduling transform, not an approximation: a draft token is accepted
only when it equals the argmax the model produces at that position
teacher-forced on the exact accepted prefix, and the first rejected
position emits that argmax instead. tests/test_speculative.py pins
token-for-token equality with ``generate_dense`` on random, repetitive,
and adversarial prompts; the speedup is the only thing that varies
(acceptance depends on how self-predictable the stream is — lookup
drafting wins on loops, templates, and copy-heavy continuations).

Cache-consistency argument (why rejected drafts never poison the KV
cache): the verify forward at cursor ``c`` writes positions
``[c-1, c+k-1]`` *before* attending (``_incremental_layer`` updates
then reads). After accepting ``m+1`` tokens the next verify starts at
``c' = c+m+1 <= c+k+1``, so its write window ``[c'-1, c'+k-1]`` covers
every stale position ``[c', c+k-1]`` left by the rejected tail —
garbage is always overwritten before any read reaches it.

Two drafters share the one verify loop (any draft is CORRECT — a bad
one just lowers acceptance, never the output):

* **n-gram (prompt lookup)**, the default: find the most recent
  earlier occurrence of the current bigram and propose the ``k``
  tokens that followed it; with no match, repeat the last token.
  Free (no extra model FLOPs) and strong on self-predictable streams
  (loops, templates, copy-heavy continuations).
* **truncated-layer model draft** (``draft_layers=d``): the first
  ``d`` layers of the SAME checkpoint plus the shared head act as the
  draft model, with their own KV cache carried through the loop. Each
  iteration teacher-forces the (k+1)-token trailing window through the
  draft stack (idempotent rewrites cover every position a rejected
  tail left stale — same overwrite-before-read argument as the verify
  cache below) and then drafts ``k`` tokens autoregressively. Costs
  ~``(d/L)·(2k)`` extra forward-fractions per iteration; wins when its
  acceptance on non-self-predictable streams beats lookup's by more
  than that — the spec rung measures both on the same stream.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
from .. import _jax_compat  # noqa: F401  (installs older-JAX aliases)
import jax.numpy as jnp
import numpy as np

from .decode import (
    _check_decode_mesh,
    _incremental_forward,
    init_cache,
    prefill_dense,
)
from .transformer import TransformerConfig

__all__ = [
    "generate_speculative_dense",
    "make_speculative_dense",
    "make_speculative",
]


def _bigram_draft(buf, cursor, k: int):
    """(L,) token buffer, known through ``cursor`` -> (k,) draft.

    Proposes the continuation of the most recent earlier occurrence of
    the last known bigram ``(buf[cursor-2], buf[cursor-1])``; falls
    back to repeating the last token. Pure device ops, O(L) compare."""
    L = buf.shape[0]
    idx = jnp.arange(L)
    a0, a1 = buf[cursor - 2], buf[cursor - 1]
    nxt = jnp.roll(buf, -1)
    match = (buf == a0) & (nxt == a1) & (idx < cursor - 2)
    p = jnp.max(jnp.where(match, idx, -1))
    has = p >= 0
    start = jnp.where(has, p + 2, cursor - 1)
    dr = jax.lax.dynamic_slice(buf, (start,), (k,))
    return jnp.where(has, dr, buf[cursor - 1])


def _make_model_draft(params_d, cfg_d: TransformerConfig, Lbuf: int,
                      k: int, **fwd_kwargs):
    """Truncated-layer draft model: ``(draft_init, draft_step)`` over a
    draft-cache state. ``draft_init(prompt, cache_d)`` prefills;
    ``draft_step(buf, cursor, cache_d) -> (draft (k,), cache_d)``
    teacher-forces the trailing (k+1) window (covering every position a
    rejected tail left stale — rewrites are idempotent) then drafts k
    tokens autoregressively."""

    def draft_init(prompt, cache_d):
        _, cache_d = _incremental_forward(
            params_d, prompt, cache_d, jnp.int32(0), cfg_d,
            prefill=True, **fwd_kwargs,
        )
        return cache_d

    def draft_step(buf, cursor, cache_d):
        off = jnp.maximum(cursor - 1 - k, 0)
        chunk = jax.lax.dynamic_slice(buf, (off,), (k + 1,))[None]
        lg, cache_d = _incremental_forward(
            params_d, chunk, cache_d, off, cfg_d, prefill=False,
            **fwd_kwargs,
        )
        # logits at local index (cursor-1)-off predict position cursor
        t0 = jnp.argmax(
            jnp.take(lg[0], cursor - 1 - off, axis=0)
        ).astype(buf.dtype)

        def sstep(carry, i):
            tok, cache_d = carry
            lg1, cache_d = _incremental_forward(
                params_d, tok[None, None], cache_d, cursor + i, cfg_d,
                prefill=False, **fwd_kwargs,
            )
            nt = jnp.argmax(lg1[0, 0]).astype(buf.dtype)
            return (nt, cache_d), tok

        (last, cache_d), toks = jax.lax.scan(
            sstep, (t0, cache_d), jnp.arange(k - 1)
        )
        return jnp.concatenate([toks, last[None]]), cache_d

    return draft_init, draft_step


def _spec_loop(prefill, step, cache, prompt, Tp: int, n_new: int,
               k: int, draft=None, dstate=()):
    """THE draft/verify loop — the exact-greedy acceptance contract
    lives here once, shared by the dense and sharded programs and by
    both drafters.

    ``prefill(prompt, cache) -> (logits (1, Tp, V), cache)``;
    ``step(chunk (1, k+1), cache, offset) -> (logits, cache)``;
    ``draft(buf, cursor, dstate) -> (draft (k,), dstate)`` — defaults
    to the stateless n-gram lookup.
    Returns the packed ``(n_new + 1,)`` array: tokens + the verify-
    forward count in the last slot (one array = one D2H fetch — two
    separate fetches cost two tunnel round trips, the difference
    between a measured win and a measured loss on the bench chip)."""
    if prompt.shape[1] != Tp:
        raise ValueError(
            f"program compiled for Tp={Tp}, got prompt of "
            f"{prompt.shape[1]} tokens: positions past the prompt "
            "would attend unwritten zero K/V and diverge silently"
        )
    if draft is None:
        def draft(buf, cursor, dstate):
            return _bigram_draft(buf, cursor, k), dstate

    Lbuf = Tp + n_new + k + 1  # slack: the last verify may overrun
    logits, cache = prefill(prompt, cache)
    first = jnp.argmax(logits[0, -1]).astype(prompt.dtype)
    buf = jnp.zeros((Lbuf,), prompt.dtype)
    buf = jax.lax.dynamic_update_slice(buf, prompt[0], (0,))
    buf = buf.at[Tp].set(first)

    def cond(state):
        _, cursor, _, _, _ = state
        return cursor < Tp + n_new

    def body(state):
        buf, cursor, cache, dstate, iters = state
        dr, dstate = draft(buf, cursor, dstate)  # (k,)
        chunk = jnp.concatenate(
            [jax.lax.dynamic_slice(buf, (cursor - 1,), (1,)), dr]
        )[None]  # (1, k+1) at positions cursor-1 .. cursor+k-1
        lg, cache = step(chunk, cache, cursor - 1)
        greedy = jnp.argmax(lg[0], axis=-1).astype(buf.dtype)  # (k+1,)
        # greedy[i] is the model's token for position cursor+i given
        # the exact prefix; accept drafts while they match it
        acc = jnp.cumprod((greedy[:k] == dr).astype(jnp.int32))
        m = jnp.sum(acc, dtype=jnp.int32)  # accepted drafts, 0..k
        draft_ext = jnp.concatenate([dr, dr[-1:]])
        # emit[i<m] = draft[i] (== greedy[i]); emit[m] = greedy[m]
        # (the correction); entries past m are dead — overwritten
        # by later iterations before any read
        emit = jnp.where(jnp.arange(k + 1) < m, draft_ext, greedy)
        buf = jax.lax.dynamic_update_slice(buf, emit, (cursor,))
        return buf, cursor + m + 1, cache, dstate, iters + 1

    buf, cursor, _, _, iters = jax.lax.while_loop(
        cond, body, (buf, jnp.int32(Tp + 1), cache, dstate,
                     jnp.int32(0))
    )
    return jnp.concatenate(
        [buf[Tp:Tp + n_new], iters.astype(buf.dtype)[None]]
    )


def _truncated(params, d: int):
    """Draft params: the first ``d`` layers + the shared embedding and
    final norm of the SAME checkpoint (no extra weights to manage)."""
    return {**params, "layers": params["layers"][:d]}


def _check_draft_layers(cfg: TransformerConfig, draft_layers):
    if draft_layers is None:
        return None
    d = int(draft_layers)
    if not 0 < d < cfg.n_layers:
        raise ValueError(
            f"draft_layers must be in [1, {cfg.n_layers - 1}] "
            f"(a strict truncation of the model), got {draft_layers}"
        )
    return d


@functools.lru_cache(maxsize=64)
def _spec_runner(cfg: TransformerConfig, Tp: int, n_new: int, k: int,
                 draft_layers: int | None = None):
    Lbuf = Tp + n_new + k + 1

    @jax.jit
    def run(params, prompt):
        cache = init_cache(cfg, 1, Lbuf)
        draft, dstate = None, ()
        if draft_layers is not None:
            cfg_d = dataclasses.replace(cfg, n_layers=draft_layers)
            params_d = _truncated(params, draft_layers)
            draft_init, draft = _make_model_draft(
                params_d, cfg_d, Lbuf, k
            )
            dstate = draft_init(prompt, init_cache(cfg_d, 1, Lbuf))
        return _spec_loop(
            lambda pr, c: prefill_dense(params, pr, c, cfg),
            lambda ch, c, off: _incremental_forward(
                params, ch, c, off, cfg, prefill=False
            ),
            cache, prompt, Tp, n_new, k, draft=draft, dstate=dstate,
        )

    return run


def make_speculative_dense(
    cfg: TransformerConfig, Tp: int, n_new: int, k: int = 4,
    *, draft_layers: int | None = None,
):
    """The raw jitted program: ``run(params, prompt (1, Tp)) ->
    (n_new + 1,) device array`` of tokens plus the verify-forward count
    in the last slot (one array = one D2H fetch). For callers that
    manage fencing themselves (benchmarks chaining several generations
    per fence); everyone else wants
    :func:`generate_speculative_dense`. ``draft_layers=d`` swaps the
    n-gram drafter for the truncated-layer model draft."""
    return _spec_runner(
        cfg, int(Tp), int(n_new), int(k),
        _check_draft_layers(cfg, draft_layers),
    )


def generate_speculative_dense(
    params, prompt, n_new: int, cfg: TransformerConfig, *, k: int = 4,
    draft_layers: int | None = None,
):
    """Greedy generation via draft-k/verify-in-one-forward speculation.

    ``prompt``: (1, Tp) int tokens, Tp >= 2 (the bigram draft needs
    one). Returns ``(tokens (1, n_new), n_forwards)`` — the token
    stream is EXACTLY ``generate_dense``'s greedy stream; the decode
    forward count is what speculation buys: ``1 + n_forwards`` total
    model calls (prefill + verifies) instead of ``1 + (n_new - 1)``,
    i.e. ``(n_new - 1) / n_forwards`` tokens per decode forward (> 1
    whenever drafts are being accepted; each verify forward is k+1
    tokens wide, so the FLOPs per forward rise — the win is real when
    decode is bandwidth/latency-bound, which is what the cache reads
    make it). Greedy only (sampling breaks the exact-equality
    contract this implementation pins)."""
    B, Tp = prompt.shape
    if B != 1:
        raise ValueError(
            f"speculative decode is per-stream (B=1), got batch {B}; "
            "vmap/shard the stream level instead"
        )
    if Tp < 2:
        raise ValueError(f"bigram drafting needs a prompt >= 2, got {Tp}")
    if n_new < 1:
        raise ValueError(f"n_new must be >= 1, got {n_new}")
    if k < 1:
        raise ValueError(f"draft length k must be >= 1, got {k}")
    packed = np.asarray(
        _spec_runner(
            cfg, Tp, n_new, int(k), _check_draft_layers(cfg, draft_layers)
        )(params, prompt)
    )
    return packed[None, :n_new], int(packed[n_new])


def make_speculative(cfg: TransformerConfig, mesh, Tp: int, n_new: int,
                     *, k: int = 4, draft_layers: int | None = None):
    """Sharded speculative generation over a (dp=1, tp) mesh:
    ``run(params, prompt (1, Tp)) -> (n_new + 1,)`` packed tokens +
    forward count, same contract as :func:`make_speculative_dense`.

    The draft/verify while_loop (``_spec_loop`` — shared with the
    dense program, so the exact-greedy acceptance logic lives once)
    runs inside ONE shard_map jit: every tp member computes identical
    post-psum logits, hence the identical argmax, draft, and
    acceptance — the speculation control flow replicates for free,
    exactly like greedy ``make_generate``'s token picks. Per-stream
    (B=1): speculation is a latency optimization for one sequence;
    shard extra streams over dp by running one program per stream.
    Dense configs only: the MoE all_to_all marks the loop carries
    varying over ep, which the replicated-control-flow scheme cannot
    express — MoE serving uses :func:`~.decode.make_generate`."""
    from jax.sharding import PartitionSpec as P

    from .decode import (
        _cache_heads_global,
        _zero_cache_layer,
        make_kv_slice,
    )
    from .transformer import param_specs

    _check_decode_mesh(cfg, mesh)
    if cfg.n_experts:
        raise ValueError(
            "sharded speculative decoding supports dense configs only "
            "(MoE expert-parallel carries cannot replicate across the "
            "speculation loop); serve MoE with make_generate"
        )
    if int(mesh.shape["dp"]) != 1:
        raise ValueError(
            "speculative decode is per-stream: use dp=1 (run one "
            "program per stream for batch serving)"
        )
    if Tp < 2 or n_new < 1 or k < 1:
        raise ValueError(f"need Tp >= 2, n_new >= 1, k >= 1; got "
                         f"{(Tp, n_new, k)}")
    draft_layers = _check_draft_layers(cfg, draft_layers)
    Lbuf = Tp + n_new + k + 1

    def local(params, prompt):
        kv_slice = make_kv_slice(cfg)
        Hc = _cache_heads_global(cfg, mesh)
        tp = mesh.shape["tp"]
        cache = [
            _zero_cache_layer(1, Lbuf, Hc // tp, cfg.head_dim,
                              cfg.dtype, False)
            for _ in range(cfg.n_layers)
        ]
        draft, dstate = None, ()
        if draft_layers is not None:
            # the draft stack shards exactly like the verify stack
            # (same tp psum, same kv slicing), so its argmax — and
            # hence the speculation control flow — replicates too
            cfg_d = dataclasses.replace(cfg, n_layers=draft_layers)
            params_d = _truncated(params, draft_layers)
            draft_init, draft = _make_model_draft(
                params_d, cfg_d, Lbuf, k,
                kv_slice=kv_slice, tp_psum=True,
            )
            cache_d = [
                _zero_cache_layer(1, Lbuf, Hc // tp, cfg.head_dim,
                                  cfg.dtype, False)
                for _ in range(draft_layers)
            ]
            dstate = draft_init(prompt, cache_d)
        return _spec_loop(
            lambda pr, c: _incremental_forward(
                params, pr, c, jnp.int32(0), cfg, prefill=True,
                kv_slice=kv_slice, tp_psum=True,
            ),
            lambda ch, c, off: _incremental_forward(
                params, ch, c, off, cfg, prefill=False,
                kv_slice=kv_slice, tp_psum=True,
            ),
            cache, prompt, Tp, n_new, k, draft=draft, dstate=dstate,
        )

    # prompt replicated (dp=1 enforced above): every member runs the
    # identical control flow on identical post-psum logits, so the
    # packed output is unvarying on every mesh axis
    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs(cfg, mesh), P()),
        out_specs=P(),
    )
    return jax.jit(f)
