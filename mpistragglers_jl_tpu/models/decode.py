"""Inference for the flagship transformer: KV cache, prefill, decode.

The reference has no inference code of any kind (it has no model code —
SURVEY §2); this is north-star flagship scope (VERDICT r3 missing #2):
a framework that trains long-context models must also serve them.

Design (TPU-first):

* **One incremental forward.** Prefill and decode are the same program
  at different chunk sizes: a chunk of ``T`` tokens at global offset
  ``off`` writes its per-layer K/V into the cache at ``[off, off+T)``
  and attends causally. Prefill (``off == 0``) needs no cache reads, so
  it runs the configured chunk kernel — the flash Pallas kernel for
  long prompts. Decode (``T == 1``) attends the single query against
  the whole cache through the grouped GQA einsums
  (:func:`~..parallel.ring_attention._group_scores`), so MQA/GQA
  configs read ``kv_heads`` cache heads, not ``n_heads`` — the KV
  bandwidth/memory win is structural, never faked by a repeat.
* **Static shapes.** The cache is ``(B, max_len, kv_heads, head_dim)``
  per layer; validity is positional masking (``kpos <= qpos``), never a
  dynamic slice length — one compiled program serves every step.
* **tp-sharded cache.** Cache heads shard over ``tp`` like the K/V
  projections. When ``kv_heads < tp`` (MQA/GQA serving with wide tp)
  the cache uses the *replicated-groups* layout: global head axis
  ``tp`` slots, slot ``t`` holding kv head ``t * kv_heads // tp`` —
  each device computes its own replica from the tp-replicated K/V
  projections, so the layout needs no extra collectives.
* **Sliding windows roll.** With ``attn_window=W`` the default path
  masks the (q-W, q] band over a ``max_len`` cache exactly like
  training; the *ring* path (``generate_ring_dense`` /
  ``make_ring_generate``) keeps an O(W) circular cache instead:
  position ``p`` writes slot ``p % W``, and slot ``s`` at decode
  position ``pos`` holds position ``kpos(s) = pos - ((pos - s) mod
  W)`` — valid iff ``kpos >= 0``, which makes the window+causal mask
  *and* the warmup masking of unwritten slots the same one predicate.
  RoPE is applied at write time with absolute positions, so rotation
  survives the permuted storage order (dot products are relative).
  Decode reads W cache positions per step regardless of how long the
  stream runs — the window's memory/bandwidth prize at W << max_len.
* **Greedy generation is one program.** ``make_generate`` runs prefill
  plus a ``lax.scan`` over decode steps *inside a single shard_map
  jit* — no host round trip per token; on the tunneled bench chip that
  is the difference between ~110 ms/token of fence RTT and pure
  device-side stepping.

Decode-time attention is exact; the teacher-forced logits equal the
training forward's (tests/test_decode.py pins both, sharded included).
One caveat: MoE expert capacity is a per-call shape, so MoE configs
tight enough to drop tokens route per chunk, not per full sequence —
see :func:`prefill_dense`.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
from .. import _jax_compat  # noqa: F401  (installs older-JAX aliases)
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.ring_attention import (
    _band_mask,
    _flash_interpreted,
    _group_pv,
    _group_scores,
    resolve_attention_impl,
)
from .moe import moe_ffn_dense, moe_ffn_sharded
from .transformer import (
    TransformerConfig,
    _kv_tp_sharded,
    _ln,
    _mlp,
    _rope,
    make_kv_slice,
    param_specs,
)

__all__ = [
    "init_cache",
    "cache_specs",
    "decode_batch_axes",
    "prefill_dense",
    "decode_step_dense",
    "decode_step_ring_dense",
    "generate_dense",
    "generate_ring_dense",
    "init_ring_cache",
    "ring_from_cache",
    "make_generate",
    "make_ring_generate",
    "make_prefill",
    "make_decode_step",
    "make_extend",
]

_NEG = -1e30  # matches parallel/ring_attention.py

# int8 decode-kernel routing (ops/decode_attention.py). Tri-state:
#
#   None  (default) — AUTO: route the kernel only for BATCHED decode
#         (local batch >= KERNEL_MIN_BATCH). Measured (docs/PERF.md):
#         standalone the kernel beats the bf16 einsum 1.22x at its DMA
#         floor, but inside the generation scan each pallas_call pays a
#         launch/carry-aliasing boundary cost of ~0.02-0.04 ms/layer.
#         That cost is PER CALL, so batching divides it by the rows the
#         call serves: at B=1 it swamps the byte win (0.70-0.91x), at
#         B >= 4 the amortized boundary rides under the streaming win.
#   True  — force the kernel at every batch (tests, attribution).
#   False — force the einsum dequant path.
_USE_DECODE_KERNEL: bool | None = None

# The auto threshold: the r5 boundary attribution (~0.03 ms/call) over
# the kernel's standalone margin (~0.012 ms at the 16k flagship shape)
# crosses under 4 rows per call; serving runs S=8.
KERNEL_MIN_BATCH = 4

_UNSET = object()  # "no snapshot" sentinel for _kernel_possible


def use_decode_kernel(enabled: bool | None) -> None:
    """Set int8 decode-attention routing: ``True`` forces the Pallas
    kernel, ``False`` forces the einsum dequant path, ``None`` restores
    the batched AUTO default (kernel iff local batch >=
    ``KERNEL_MIN_BATCH`` — see the module note). The flag is part of
    the dense runners' cache key, so toggling always takes effect on
    the next dense ``generate_*`` call — already-compiled programs for
    the other setting stay cached and are reused on a toggle back.
    ``make_*`` closures snapshot the flag at *make* time (routing and
    shard_map's vma setting must agree); rebuild them to change
    routing."""
    global _USE_DECODE_KERNEL
    _USE_DECODE_KERNEL = None if enabled is None else bool(enabled)


def _decode_kernel_enabled() -> bool | None:
    return _USE_DECODE_KERNEL


def _route_kernel(use_kernel, B: int) -> bool:
    """Resolve the tri-state toggle at a concrete (trace-time) local
    batch. ``_UNSET`` reads the live global; an explicit ``None`` is a
    caller's make-time AUTO snapshot and resolves WITHOUT re-reading
    the global — routing and the snapshot-derived ``check_vma`` setting
    must come from ONE reading (make_generate / make_serving_scan), or
    a toggle flipped between make and first trace would bake a program
    whose routing disagrees with its vma mode. AUTO routes the kernel
    only when the call serves enough rows (``KERNEL_MIN_BATCH``) to
    amortize the scan/custom_call boundary cost."""
    if use_kernel is _UNSET:
        use_kernel = _USE_DECODE_KERNEL
    if use_kernel is None:
        return B >= KERNEL_MIN_BATCH
    return bool(use_kernel)


def _kernel_viable(q, cache_l) -> bool:
    """Trace-time shape gate shared by EVERY int8-kernel call site
    (masked ``_cached_attention``, ring ``_ring_cached_attention``,
    and serving's per-row ``_ring_attention_rows``): quantized cache,
    single query, lane-aligned head_dim, a GQA group that fits the
    kernel's 8 sublanes (ops/decode_attention._SUB), and a 128-multiple
    block divisor for the cache length. One predicate so the routing
    sites cannot drift from the kernel's actual constraints."""
    if not _is_quantized(cache_l):
        return False
    Hq, Hkv = q.shape[2], cache_l["k"].shape[2]
    if (
        q.shape[1] != 1
        or q.shape[-1] % 128 != 0
        or Hq % Hkv != 0
        or Hq // Hkv > 8
    ):
        return False
    from ..ops.decode_attention import DEFAULT_BLOCK_K, _pick_block_128

    return _pick_block_128(
        cache_l["k"].shape[1], DEFAULT_BLOCK_K, Hkv, q.shape[-1]
    ) is not None


def _kernel_possible(cfg, quantize_kv: bool, use_kernel=_UNSET) -> bool:
    """Could a program for ``cfg`` route T=1 cached attention through
    the int8 kernel? The shard-invariant part of ``_cached_attention``'s
    guard (toggle not forced off, quantized cache, lane-aligned
    head_dim); the remaining conditions (GQA ratio, block divisor,
    batch threshold under auto) depend on per-shard shapes and stay
    trace-time. Used both to keep the flag out of cache keys where it
    is inert and to scope the vma carve-out. ``None`` (auto) counts as
    possible — the batch is not known here."""
    if use_kernel is _UNSET:
        use_kernel = _USE_DECODE_KERNEL
    return bool(
        quantize_kv and use_kernel is not False
        and cfg.head_dim % 128 == 0
    )


def _paged_kernel_possible(cfg, quantize_kv: bool, page_tokens: int,
                           use_kernel=_UNSET) -> bool:
    """Could the PAGED serving tick route the int8 kernel's page-table
    mode? ``_kernel_possible``'s cfg-static guard plus the paged-only
    conditions the dense gather fallback does not have: the GQA group
    must fit the kernel's 8-row tile (trace-time in the dense path,
    cfg-static here — the serving tick fixes its routing at
    construction) and the page size must be a streamable k-block
    (``ops.decode_attention.paged_block_viable``). The serving
    scheduler resolves this ONCE at construction against its slot
    count; there is no trace-time re-gate on the paged path."""
    if not _kernel_possible(cfg, quantize_kv, use_kernel):
        return False
    if cfg.n_heads // cfg.kv_heads > 8 or cfg.n_heads % cfg.kv_heads:
        return False
    from ..ops.decode_attention import paged_block_viable

    return paged_block_viable(page_tokens)


def _decode_kernel_interpreted(
    cfg, quantize_kv: bool, use_kernel=_UNSET
) -> bool:
    """True iff a quantized decode program for ``cfg`` could trace the
    int8 Pallas kernel via the Pallas *interpreter* (non-TPU mesh) —
    shard_map's varying-axes checking must be off for it, the same
    carve-out ``_flash_interpreted`` gives the flash kernels.
    ``use_kernel`` is the make-time snapshot of the toggle; defaults to
    the live flag. A slight over-approximation is safe only in one
    direction: claiming "kernel" for a kernel-free program silently
    loses vma checking, so the cfg-static guard conditions are all
    applied here. Under the AUTO default the per-shard batch is not
    known at make time, so auto counts as "kernel" — a small-batch
    auto program on an interpreted mesh runs without vma checking (the
    conservative direction is unreachable without the batch)."""
    if not _kernel_possible(cfg, quantize_kv, use_kernel):
        return False
    from ..ops.flash_attention import _use_interpret

    return _use_interpret()


# --------------------------------------------------------------------------
# int8 KV-cache quantization (serving-time choice, orthogonal to layout)
# --------------------------------------------------------------------------


def _kv_quantize(x):
    """Per-(batch, position, head) absmax int8 quantization over the
    head_dim axis: ``x ~= x_i8 * s[..., None]``. The scale axis choice
    matters: per-position scales ride the cache (tiny — no D axis) and
    dequantization folds into the attention einsums as a rank-1 scale
    on scores (K) and probabilities (V), so no dequantized copy is
    *required* at full size. Measured reality (docs/PERF.md): XLA
    materializes one anyway before the dot, so on the current
    toolchain this is a MEMORY feature (half the cache bytes), not a
    latency feature."""
    xf = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(xf), axis=-1) / 127.0
    s = jnp.maximum(s, 1e-8)  # all-zero rows (unwritten slots)
    return jnp.round(xf / s[..., None]).astype(jnp.int8), s


def _is_quantized(cache_l: dict) -> bool:
    return "k_s" in cache_l


def _expand_kv_scale(s, Hq):
    """(B, L, Hkv) per-position scales -> (B, Hq, 1, L) broadcastable
    against (B, Hq, Lq, L) scores/probs, repeating each kv head's scale
    over its GQA group (same grouping as ``_group_scores``)."""
    g = Hq // s.shape[2]
    if g > 1:
        s = jnp.repeat(s, g, axis=2)
    return s.transpose(0, 2, 1)[:, :, None, :]


def _cache_write(cache_l: dict, k, v, off) -> dict:
    """Write a chunk's K/V at position-axis offset ``off``, quantizing
    when the cache is int8 (detected from the layout, so every caller
    — masked, ring, chunked — shares one write path)."""
    upd = partial(jax.lax.dynamic_update_slice_in_dim, start_index=off,
                  axis=1)
    if not _is_quantized(cache_l):
        return {"k": upd(cache_l["k"], update=k),
                "v": upd(cache_l["v"], update=v)}
    kq, ks = _kv_quantize(k)
    vq, vs = _kv_quantize(v)
    return {
        "k": upd(cache_l["k"], update=kq),
        "v": upd(cache_l["v"], update=vq),
        "k_s": upd(cache_l["k_s"], update=ks),
        "v_s": upd(cache_l["v_s"], update=vs),
    }


def _cache_scores(q, cache_l: dict, scale):
    """Grouped scores against the cache, dequantizing via the rank-1
    score correction when int8."""
    kc = cache_l["k"]
    if not _is_quantized(cache_l):
        return _group_scores(q, kc, scale)
    s = _group_scores(q, kc.astype(q.dtype), scale)
    return s * _expand_kv_scale(cache_l["k_s"], q.shape[2])


def _cache_pv(p, cache_l: dict):
    """Grouped probs x V against the cache; int8 V dequantizes by
    folding the per-position scale into the probabilities."""
    if _is_quantized(cache_l):
        p = p * _expand_kv_scale(cache_l["v_s"], p.shape[1])
    return _group_pv(p, cache_l["v"])


def _cache_heads_global(cfg: TransformerConfig, mesh: Mesh | None) -> int:
    """Global cache head count: ``kv_heads``, or ``tp`` replicated-group
    slots when kv_heads < tp (see module docstring)."""
    if mesh is None or "tp" not in mesh.axis_names:
        return cfg.kv_heads
    tp = mesh.shape["tp"]
    return cfg.kv_heads if _kv_tp_sharded(cfg, mesh) else tp


def _zero_cache_layer(B, L, H, Dh, dtype, quantize_kv):
    z = jnp.zeros((B, L, H, Dh), jnp.int8 if quantize_kv else dtype)
    layer = {"k": z, "v": z}
    if quantize_kv:
        zs = jnp.zeros((B, L, H), jnp.float32)
        layer["k_s"], layer["v_s"] = zs, zs
    return layer


def init_cache(
    cfg: TransformerConfig, batch: int, max_len: int,
    mesh: Mesh | None = None, *, quantize_kv: bool = False,
) -> list[dict]:
    """Zeroed per-layer KV cache (host pytree; ``shard_cache`` places
    it). Layout: layers -> {"k","v"} of (B, max_len, cache_heads, Dh);
    ``quantize_kv=True`` stores int8 K/V plus per-(batch, position,
    head) f32 scales ``{"k_s","v_s"}`` — half the bytes of a bf16
    cache, dequantized inside the attention einsums (never at full
    size)."""
    H = _cache_heads_global(cfg, mesh)
    return [
        _zero_cache_layer(batch, max_len, H, cfg.head_dim, cfg.dtype,
                          quantize_kv)
        for _ in range(cfg.n_layers)
    ]


def decode_batch_axes(cfg: TransformerConfig) -> tuple[str, ...]:
    """Mesh axes the batch shards over at decode: MoE configs add
    ``ep`` (every expert-parallel member routes distinct rows — the
    GShard layout, matching the training path's ``batch_axes``)."""
    return ("dp", "ep") if cfg.n_experts else ("dp",)


def cache_specs(cfg: TransformerConfig, *,
                quantize_kv: bool = False) -> list[dict]:
    """PartitionSpecs for the cache: batch over dp (and ep for MoE),
    heads over tp; int8 scales shard exactly like their K/V."""
    s = P(decode_batch_axes(cfg), None, "tp", None)
    layer = {"k": s, "v": s}
    if quantize_kv:
        ss = P(decode_batch_axes(cfg), None, "tp")
        layer["k_s"], layer["v_s"] = ss, ss
    return [dict(layer) for _ in range(cfg.n_layers)]


def shard_cache(cache, cfg: TransformerConfig, mesh: Mesh):
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        cache, cache_specs(cfg, quantize_kv=_is_quantized(cache[0])),
    )


def _cached_attention(q, cache_l, qpos, scale, window=None,
                      use_kernel=_UNSET):
    """Grouped attention of the chunk's queries against the full cache.

    q: (B, T, H, D); the cache holds (B, Lmax, Hkv, D) at positions
    ``arange(Lmax)``; validity is ``kpos <= qpos`` (cache entries past
    the chunk are zeros AND masked; entries below the offset are real),
    intersected with the sliding-window band when ``window`` is set.

    int8 caches at T == 1 take the Pallas decode kernel
    (ops/decode_attention.py): it dequantizes in VMEM, so HBM reads
    really are the int8 bytes — the einsum form's ``.astype`` is
    materialized by XLA and gives half the bytes back (docs/PERF.md).
    ``use_kernel`` pins the routing decision (callers that also pick a
    vma setting from it must pass their snapshot — even an AUTO
    ``None`` snapshot resolves without re-reading the global, see
    ``_route_kernel``); the ``_UNSET`` default reads the global toggle,
    whose AUTO default routes the kernel only for batched calls (the
    per-call scan boundary cost amortizes over the batch rows).
    """
    if _route_kernel(use_kernel, q.shape[0]) and _kernel_viable(
        q, cache_l
    ):
        from ..ops.decode_attention import quantized_decode_attention

        return quantized_decode_attention(
            q, cache_l, qpos[0], scale, window
        )
    Lmax = cache_l["k"].shape[1]
    s = _cache_scores(q, cache_l, scale)  # (B, H, T, Lmax) f32
    # the one band predicate (parallel/ring_attention._band_mask): the
    # serving path cannot silently diverge from the training oracle
    mask = _band_mask(qpos, jnp.arange(Lmax), True, window)
    s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    o = _cache_pv(p, cache_l)  # (B, T, H, D) f32
    return o.astype(q.dtype)


def _ring_cached_attention(q, cache_l, pos, scale, use_kernel=_UNSET):
    """Single-query attention against an O(W) ring cache.

    q: (B, 1, H, D); the cache holds (B, W, Hkv, D) where slot ``s``
    holds the K/V of position ``kpos(s) = pos - ((pos - s) mod W)``
    (the module docstring's invariant, established by the prefill
    gather and maintained by the per-step slot write). ``kpos >= 0`` is
    the whole mask: it is simultaneously the causal bound (every stored
    position is <= pos by construction), the sliding-window bound
    (every stored position is > pos - W), and the warmup guard for
    slots no position has reached yet.

    int8 ring caches route the same Pallas kernel as the masked path
    when the routing gate says so (``ring=True`` mode evaluates the
    identical ``kpos >= 0`` predicate in VMEM) — the window serving
    scan gets the dequantize-in-registers win at batch."""
    W = cache_l["k"].shape[1]
    if _route_kernel(use_kernel, q.shape[0]) and _kernel_viable(
        q, cache_l
    ):
        from ..ops.decode_attention import quantized_decode_attention

        return quantized_decode_attention(
            q, cache_l, pos, scale, ring=True
        )
    s = _cache_scores(q, cache_l, scale)  # (B, H, 1, W) f32
    kpos = pos - jnp.mod(pos - jnp.arange(W), W)
    s = jnp.where((kpos >= 0)[None, None, None, :], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    o = _cache_pv(p, cache_l)  # (B, 1, H, D) f32
    return o.astype(q.dtype)


def _incremental_layer(x, lp, cache_l, qpos, cfg, *, chunk_attn, kv_slice,
                       tp_psum, ring=False, decode_kernel=_UNSET):
    """One layer of the incremental forward: write the chunk's K/V into
    the cache at ``qpos`` positions, attend, MLP. Returns (x, cache_l).
    ``tp_psum=True`` combines the head-shard out-projection and the
    d_ff-shard down-projection over the ``tp`` axis, exactly like the
    training path (models/transformer.py ``_forward_local``).
    ``ring=True`` treats the cache as the O(W) circular window buffer
    (single-token chunks only): the write lands at slot ``pos % W`` and
    attention runs through :func:`_ring_cached_attention`."""
    h = _ln(x, lp["ln1_s"], lp["ln1_b"])
    q = jnp.einsum("bld,dhk->blhk", h, lp["wq"])
    k = jnp.einsum("bld,dhk->blhk", h, lp["wk"])
    v = jnp.einsum("bld,dhk->blhk", h, lp["wv"])
    if kv_slice is not None:
        k, v = kv_slice(k), kv_slice(v)
    q, k = _rope(q, qpos), _rope(k, qpos)
    off = qpos[0]
    if ring:
        off = jnp.mod(off, cache_l["k"].shape[1])
    cache_l = _cache_write(cache_l, k, v, off)
    scale = cfg.head_dim ** -0.5
    if chunk_attn is not None:
        # prefill at offset 0: attention lives entirely inside the chunk,
        # so the configured chunk kernel (flash on TPU) does the work on
        # the exact (unquantized) chunk K/V — only the cache quantizes
        o = chunk_attn(q, k, v)
    elif ring:
        o = _ring_cached_attention(q, cache_l, qpos[0], scale,
                                   use_kernel=decode_kernel)
    else:
        o = _cached_attention(q, cache_l, qpos, scale, cfg.attn_window,
                              use_kernel=decode_kernel)
    attn_out = jnp.einsum("blhk,hkd->bld", o, lp["wo"])
    if tp_psum:
        attn_out = jax.lax.psum(attn_out, "tp")
    x = x + attn_out
    h2 = _ln(x, lp["ln2_s"], lp["ln2_b"])
    if cfg.n_experts:
        if tp_psum:
            # inside the mesh program: expert-parallel routing, exactly
            # the training path's MoE branch (_forward_local) — experts
            # over ep via all_to_all, hidden dims over tp
            y, ybias, _ = moe_ffn_sharded(h2, lp, cfg.capacity_factor)
            x = x + jax.lax.psum(y, "tp") + ybias
        else:
            x = x + moe_ffn_dense(h2, lp, cfg.capacity_factor)[0]
    else:
        y = _mlp(h2, lp)
        if tp_psum:
            y = jax.lax.psum(y, "tp")
        x = x + y + lp["b2"]
    return x, cache_l


def _incremental_forward(params, tokens, cache, offset, cfg,
                         *, prefill, kv_slice=None, tp_psum=False,
                         ring=False, decode_kernel=_UNSET):
    """Chunk forward at global ``offset``; returns (logits, cache).

    ``prefill=True`` (static) means offset is known to be 0 and chunk
    attention uses the configured kernel; otherwise attention runs
    against the cache — the ``max_len`` positional cache by default,
    the O(W) ring buffer when ``ring=True``. ``decode_kernel`` is the
    caller's make-time snapshot of the int8-kernel toggle — a ``None``
    snapshot pins AUTO without re-reading the global (``_route_kernel``)
    — or ``_UNSET`` (the default) to read the live global at trace
    time.
    """
    T = tokens.shape[1]
    if ring and (T != 1 or prefill):
        raise ValueError(
            "ring cache reads are decode-only (T == 1): prefill runs "
            "positionally, then _ring_from_cache gathers the window"
        )
    qpos = offset + jnp.arange(T)
    chunk_attn = None
    if prefill:
        chunk_attn = partial(
            resolve_attention_impl(cfg.attn_impl), causal=True,
            window=cfg.attn_window,
        )
    x = params["emb"][tokens]
    new_cache = []
    for lp, cache_l in zip(params["layers"], cache):
        x, cache_l = _incremental_layer(
            x, lp, cache_l, qpos, cfg,
            chunk_attn=chunk_attn, kv_slice=kv_slice, tp_psum=tp_psum,
            ring=ring, decode_kernel=decode_kernel,
        )
        new_cache.append(cache_l)
    x = _ln(x, params["lnf_s"], params["lnf_b"])
    logits = jnp.einsum("bld,vd->blv", x, params["emb"])
    return logits, new_cache


# --------------------------------------------------------------------------
# dense (single-device oracle) API
# --------------------------------------------------------------------------


def _check_prefill_fits(T: int, cache) -> None:
    """Trace-time guard: ``dynamic_update_slice`` CLAMPS out-of-range
    offsets, so an over-long chunk would silently wrap the tail of the
    cache instead of erroring."""
    Lmax = jax.tree.leaves(cache)[0].shape[1]
    if T > Lmax:
        raise ValueError(
            f"chunk of {T} tokens does not fit the cache (max_len "
            f"{Lmax}); build the cache at least prompt+decode long"
        )


def _aligned_quantized_prefill(params, prompt, cache, cfg, *,
                               decode_kernel, kv_slice=None,
                               tp_psum=False, chunk=512):
    """Quantized-ring ORACLE prefill, in C-token chunks: every chunk
    attends the ALREADY-QUANTIZED cache (``prefill=False``), which is
    the only math the serving scheduler's chunked admission can ever
    evaluate — raw K/V of earlier chunks are gone once written. Per-
    position absmax quantization makes the chunk size invisible (a
    position's scale never depends on its neighbours), so any C yields
    the identical stream; C=512 keeps the materialized causal scores at
    O(C * Tp) per layer instead of the O(Tp^2) a one-shot aligned call
    would allocate — the flagship 16k prompt stays servable through
    this path, not just test-scale oracles.

    The shape-identical full chunks run under ONE ``lax.scan`` body
    (their logits are discarded; only the cache carries), so trace and
    compile cost stay flat in Tp — a python loop would retrace the
    whole per-layer forward Tp/C times. At most two chunks trace
    directly at the tail: the one whose logits the caller needs, plus
    the ragged remainder when Tp % C != 0."""
    B, Tp = prompt.shape
    _check_prefill_fits(Tp, cache)
    nfull, rem = divmod(Tp, chunk)
    # fold all full chunks whose logits nobody reads into the scan
    nscan = nfull - (1 if rem == 0 else 0)
    off0 = 0
    if nscan >= 2:
        chunks = (
            prompt[:, :nscan * chunk]
            .reshape(B, nscan, chunk)
            .swapaxes(0, 1)
        )
        offs = jnp.arange(nscan, dtype=jnp.int32) * chunk

        def body(cache, xs):
            ch, off = xs
            _, cache = _incremental_forward(
                params, ch, cache, off, cfg, prefill=False,
                kv_slice=kv_slice, tp_psum=tp_psum,
                decode_kernel=decode_kernel,
            )
            return cache, None

        cache, _ = jax.lax.scan(body, cache, (chunks, offs))
        off0 = nscan * chunk
    logits = None
    for off in range(off0, Tp, chunk):
        logits, cache = _incremental_forward(
            params, prompt[:, off:off + chunk], cache, jnp.int32(off),
            cfg, prefill=False, kv_slice=kv_slice, tp_psum=tp_psum,
            decode_kernel=decode_kernel,
        )
    return logits, cache


def prefill_dense(params, tokens, cache, cfg: TransformerConfig):
    """Fill the cache from a prompt; returns (logits (B, T, V), cache).

    MoE caveat: expert *capacity* is a per-call shape (ceil of
    tokens-routed-per-expert x capacity_factor, models/moe.py), so a
    config tight enough to DROP tokens can drop differently here than
    in the full-sequence training forward — teacher-forced equality
    holds exactly whenever no drops occur (generous capacity_factor or
    single-step decode, where capacity >= 1 covers every token)."""
    _check_prefill_fits(tokens.shape[1], cache)
    return _incremental_forward(
        params, tokens, cache, jnp.int32(0), cfg, prefill=True
    )


def decode_step_dense(params, token, cache, pos, cfg: TransformerConfig):
    """One decode step: ``token`` (B,) at global position ``pos``
    (scalar; caller keeps pos < the cache's max_len — out-of-range
    writes clamp, they do not error). Returns (logits (B, V), cache)."""
    logits, cache = _incremental_forward(
        params, token[:, None], cache, pos, cfg, prefill=False
    )
    return logits[:, 0], cache


# --------------------------------------------------------------------------
# O(W) ring cache for sliding-window serving
# --------------------------------------------------------------------------


def _check_ring_cfg(cfg: TransformerConfig) -> int:
    if cfg.attn_window is None:
        raise ValueError(
            "the ring cache is the sliding-window cache: set "
            "TransformerConfig(attn_window=W) to use it (full-attention "
            "configs need every position — use the max_len cache)"
        )
    return cfg.attn_window


def init_ring_cache(
    cfg: TransformerConfig, batch: int, mesh: Mesh | None = None, *,
    quantize_kv: bool = False,
) -> list[dict]:
    """Zeroed per-layer ring cache: layers -> {"k","v"} of
    (B, attn_window, cache_heads, Dh). Sharding specs are
    :func:`cache_specs` (the layouts coincide; only the length axis'
    meaning differs — slots, not positions)."""
    W = _check_ring_cfg(cfg)
    H = _cache_heads_global(cfg, mesh)
    return [
        _zero_cache_layer(batch, W, H, cfg.head_dim, cfg.dtype,
                          quantize_kv)
        for _ in range(cfg.n_layers)
    ]


def _ring_from_cache(cache_l: dict, Tp: int, W: int) -> dict:
    """Gather a positional cache holding positions [0, Tp) into the ring
    layout: slot ``s`` <- the latest prompt position congruent to ``s``
    (mod W); slots no position has reached (Tp < W) stay zero — the
    ``kpos >= 0`` read mask of :func:`_ring_cached_attention` already
    treats them as unwritten. Every cache leaf (int8 scales included)
    shares the position axis, so one gather covers the layout."""
    s = jnp.arange(W)
    p = (Tp - 1) - jnp.mod((Tp - 1) - s, W)
    valid = p >= 0

    def gather(a):
        g = jnp.take(a, jnp.maximum(p, 0), axis=1)
        return jnp.where(valid.reshape((1, W) + (1,) * (a.ndim - 2)), g, 0)

    return {kk: gather(a) for kk, a in cache_l.items()}


def ring_from_cache(cache, Tp: int, cfg: TransformerConfig) -> list[dict]:
    """Public positional-prefill -> ring handoff: convert a full cache
    holding prompt positions ``[0, Tp)`` (from :func:`prefill_dense`
    over an :func:`init_cache` arena) into the O(W) ring layout that
    :func:`decode_step_ring_dense` consumes. The source cache must
    actually hold every prompt position — prefilling directly into a
    W-slot ring arena would need wrapped writes the positional prefill
    does not do (:func:`_check_prefill_fits` rejects that at trace
    time); prefill long prompts into a Tp-length positional cache, then
    hand off here."""
    W = _check_ring_cfg(cfg)
    if not cache or jax.tree.leaves(cache[0])[0].shape[1] < Tp:
        have = jax.tree.leaves(cache[0])[0].shape[1] if cache else 0
        raise ValueError(
            f"source cache holds {have} positions < prompt {Tp}; the "
            "ring gather needs every prompt position present"
        )
    return [_ring_from_cache(cl, Tp, W) for cl in cache]


def decode_step_ring_dense(params, token, cache, pos,
                           cfg: TransformerConfig):
    """One decode step against the O(W) ring cache: ``token`` (B,) at
    global position ``pos``. Returns (logits (B, V), cache). Unlike
    :func:`decode_step_dense` there is no max_len to overflow — the
    stream may run indefinitely; the model simply never sees past the
    window."""
    _check_ring_cfg(cfg)
    logits, cache = _incremental_forward(
        params, token[:, None], cache, pos, cfg, prefill=False, ring=True
    )
    return logits[:, 0], cache


def _pick_token(logits, pos, key, temperature, top_k, dtype, row0=0):
    """Next-token choice shared by the dense and sharded generators:
    greedy at ``temperature == 0`` (static), else softmax sampling at
    the given temperature, optionally truncated to the top-k logits.

    The per-draw key folds the global position AND the GLOBAL batch
    row (``row0`` = this shard's batch offset under shard_map, the
    mixed-radix index over ``decode_batch_axes`` times B_local): a
    fixed key then yields one stream per
    (row, position) regardless of how the batch is sharded — dense and
    dp-sharded programs sample identical tokens, and every tp member
    draws the same token from the identical post-psum logits."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(dtype)
    lg = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        # lax.top_k's partial reduction, NOT a full-vocab sort: this
        # runs per token inside the latency-critical decode scan
        kth = jax.lax.top_k(lg, int(top_k))[0][..., -1:]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    kpos = jax.random.fold_in(key, pos)
    rows = row0 + jnp.arange(lg.shape[0])
    return jax.vmap(
        lambda r, ll: jax.random.categorical(
            jax.random.fold_in(kpos, r), ll
        )
    )(rows, lg).astype(dtype)


def _check_sampling_params(temperature, top_k) -> None:
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")


def _check_sampling(temperature, top_k, key) -> None:
    _check_sampling_params(temperature, top_k)
    if temperature == 0.0 and key is not None:
        raise ValueError("a PRNG key is only meaningful with "
                         "temperature > 0 (greedy decoding is "
                         "deterministic)")
    if temperature > 0.0 and key is None:
        raise ValueError("temperature > 0 needs a jax.random key")


def _eos_clamp(nxt, tok, done, eos_id):
    """Static-shape EOS handling: once a row has emitted ``eos_id``
    every later token is forced to it (the scan always runs n_new
    steps — shapes never depend on content; callers strip the EOS tail
    host-side). Returns (next_token, next_done)."""
    if eos_id is None:
        return nxt, done
    done = jnp.logical_or(done, tok == eos_id)
    return jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt), done


@functools.lru_cache(maxsize=64)
def _dense_runner(cfg: TransformerConfig, B: int, Tp: int, n_new: int,
                  max_len: int, temperature: float, top_k: int | None,
                  eos_id: int | None, quantize_kv: bool,
                  ring: bool = False, use_kernel: bool = False):
    """Shape-keyed jitted prefill+scan generation program (one compile
    per (cfg, shapes, sampling); the cache is built inside the jit, not
    baked in as a constant). ``ring=True`` is the O(W) sliding-window
    variant: prefill fills a Tp-length transient positional cache
    (freed after the gather), the last-W K/V gathers into ring slots,
    and the decode scan carries W positions per layer (``max_len`` is
    ignored — the ring has no horizon).

    Quantized RING prefill attends through the masked cached-attention
    path (``prefill=False`` at offset 0) instead of the exact chunk
    kernel: the serving scheduler's chunked admission can only ever
    attend the already-quantized cache (raw K/V of earlier chunks are
    gone once written), and per-position quantization makes one
    whole-prompt "chunk" here IDENTICAL to the scheduler's C-token
    chunks — so ``generate_ring_dense(quantize_kv=True)`` is the
    scheduler's stream as an IDENTITY, not a coincidence
    (tests/test_serving.py pins it). The masked (non-ring) generator
    keeps the exact-prefill property docs/PERF.md documents; the
    aligned prefill runs CHUNKED (``_aligned_quantized_prefill``), so
    its score memory is O(C * Tp) and long prompts stay servable."""
    W = _check_ring_cfg(cfg) if ring else None

    @jax.jit
    def run(params, prompt, key):
        c = init_cache(cfg, B, Tp if ring else max_len,
                       quantize_kv=quantize_kv)
        if ring and quantize_kv:
            logits, c = _aligned_quantized_prefill(
                params, prompt, c, cfg, decode_kernel=use_kernel,
            )
        else:
            logits, c = prefill_dense(params, prompt, c, cfg)
        if ring:
            c = [_ring_from_cache(cl, Tp, W) for cl in c]
        tok = _pick_token(
            logits[:, -1], Tp - 1, key, temperature, top_k, prompt.dtype
        )
        done = jnp.zeros((B,), bool)

        def step(carry, pos):
            tok, done, c = carry
            lg, c = _incremental_forward(
                params, tok[:, None], c, pos, cfg, prefill=False,
                ring=ring, decode_kernel=use_kernel,
            )
            nxt = _pick_token(
                lg[:, 0], pos, key, temperature, top_k, tok.dtype
            )
            nxt, done = _eos_clamp(nxt, tok, done, eos_id)
            return (nxt, done, c), tok

        # n_new - 1 decode forwards: the last emitted token is the final
        # carry, so no forward is spent computing a discarded successor
        (tok, _, _), toks = jax.lax.scan(
            step, (tok, done, c), Tp + jnp.arange(n_new - 1)
        )
        toks = jnp.concatenate([toks, tok[None]], axis=0)
        return toks.swapaxes(0, 1)  # (B, n_new)

    return run


def generate_dense(params, prompt, n_new: int, cfg: TransformerConfig,
                   max_len: int | None = None, *,
                   temperature: float = 0.0, top_k: int | None = None,
                   key=None, eos_id: int | None = None,
                   quantize_kv: bool = False):
    """Generation, dense single-program: prefill + lax.scan of decode
    steps under one jit (compiled once per shape, cached). Greedy by
    default; ``temperature > 0`` samples (optionally top-k-truncated)
    with the given ``key``. ``eos_id``: rows that emit it keep emitting
    it (static shapes; strip the tail host-side). Returns (B, n_new)
    tokens."""
    if n_new < 1:
        raise ValueError(f"n_new must be >= 1, got {n_new}")
    _check_sampling(temperature, top_k, key)
    B, Tp = prompt.shape
    if max_len is None:
        max_len = Tp + n_new
    if max_len < Tp + n_new:
        raise ValueError(
            f"max_len {max_len} < prompt {Tp} + n_new {n_new}: decode "
            "positions would clamp into the last cache slot"
        )
    if key is None:
        key = jax.random.key(0)  # unused at temperature 0
    return _dense_runner(
        cfg, B, Tp, n_new, max_len, float(temperature), top_k, eos_id,
        quantize_kv,
        use_kernel=_kernel_possible(cfg, quantize_kv)
        and _route_kernel(_UNSET, B),
    )(params, prompt, key)


def generate_ring_dense(params, prompt, n_new: int,
                        cfg: TransformerConfig, *,
                        temperature: float = 0.0, top_k: int | None = None,
                        key=None, eos_id: int | None = None,
                        quantize_kv: bool = False):
    """Sliding-window generation over the O(W) ring cache, dense
    single-program. Token-for-token equal to :func:`generate_dense` on
    a window config (both attend exactly the (pos-W, pos] band; only
    storage differs) while the decode scan carries W cache positions
    per layer instead of ``Tp + n_new`` — memory AND per-step cache
    bandwidth are O(W). Returns (B, n_new) tokens.

    With ``quantize_kv=True`` this is THE serving oracle: prefill
    attends the already-quantized cache exactly like the scheduler's
    chunked admission (see :func:`_dense_runner`), so a scheduler slot
    reproduces this stream as an identity; the masked generator keeps
    exact prefill, so the two quantized generators may differ at
    prefill-adjacent tokens (tests pin each contract separately)."""
    if n_new < 1:
        raise ValueError(f"n_new must be >= 1, got {n_new}")
    _check_ring_cfg(cfg)
    _check_sampling(temperature, top_k, key)
    B, Tp = prompt.shape
    if key is None:
        key = jax.random.key(0)  # unused at temperature 0
    return _dense_runner(
        cfg, B, Tp, n_new, 0, float(temperature), top_k, eos_id,
        quantize_kv, ring=True,
        # the ring kernel (ops/decode_attention ring=True) routes under
        # the same gate as the masked path
        use_kernel=_kernel_possible(cfg, quantize_kv)
        and _route_kernel(_UNSET, B),
    )(params, prompt, key)


# --------------------------------------------------------------------------
# sharded (dp [x ep] x tp mesh) API
# --------------------------------------------------------------------------


def _check_decode_mesh(cfg: TransformerConfig, mesh: Mesh):
    """MoE decode composes expert parallelism: the mesh must carry an
    ``ep`` axis (size 1 folds experts onto each member) alongside dp
    and tp — same layout as the training path."""
    need = {"dp", "tp"} | ({"ep"} if cfg.n_experts else set())
    missing = need - set(mesh.axis_names)
    if missing:
        raise ValueError(
            f"decode mesh is missing axes {sorted(missing)}; MoE "
            "configs shard over (dp, ep, tp), dense over (dp, tp)"
        )


def make_prefill(cfg: TransformerConfig, mesh: Mesh, *,
                 quantize_kv: bool = False):
    """Jitted sharded prefill: (params, tokens (B, Tp), cache) ->
    (last-position logits (B, V), cache). Batch over dp (and ep for
    MoE — expert routing runs sharded, all_to_all over ep, exactly as
    in training), heads over tp. ``quantize_kv`` must match the cache
    layout (init_cache's flag)."""
    _check_decode_mesh(cfg, mesh)
    bax = decode_batch_axes(cfg)
    cspecs = cache_specs(cfg, quantize_kv=quantize_kv)

    def local(params, tokens, cache):
        _check_prefill_fits(tokens.shape[1], cache)
        logits, cache = _incremental_forward(
            params, tokens, cache, jnp.int32(0), cfg, prefill=True,
            kv_slice=make_kv_slice(cfg), tp_psum=True,
        )
        return logits[:, -1], cache

    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs(cfg, mesh), P(bax, None), cspecs),
        out_specs=(P(bax, None), cspecs),
        check_vma=not _flash_interpreted(cfg.attn_impl),
    )
    return jax.jit(f)


def make_decode_step(cfg: TransformerConfig, mesh: Mesh, *,
                     quantize_kv: bool = False):
    """Jitted sharded decode step: (params, token (B,), cache, pos) ->
    (logits (B, V), cache). Donates the cache for in-place HBM update.
    """

    _check_decode_mesh(cfg, mesh)
    bax = decode_batch_axes(cfg)
    cspecs = cache_specs(cfg, quantize_kv=quantize_kv)
    # snapshot the kernel toggle NOW: routing (traced at first call)
    # and check_vma (fixed here) must come from the same reading, or a
    # toggle between make and first call splits them
    use_kernel = _decode_kernel_enabled()

    def local(params, token, cache, pos):
        logits, cache = _incremental_forward(
            params, token[:, None], cache, pos, cfg, prefill=False,
            kv_slice=make_kv_slice(cfg), tp_psum=True,
            decode_kernel=use_kernel,
        )
        return logits[:, 0], cache

    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(
            param_specs(cfg, mesh), P(bax), cspecs, P(),
        ),
        out_specs=(P(bax, None), cspecs),
        # decode traces no FLASH kernel, but with quantize_kv + the
        # kernel toggle it traces the int8 decode kernel — which needs
        # the same interpreted-Pallas vma carve-out
        check_vma=not _decode_kernel_interpreted(cfg, quantize_kv, use_kernel),
    )
    return jax.jit(f, donate_argnums=(2,))


def make_extend(cfg: TransformerConfig, mesh: Mesh, *,
                quantize_kv: bool = False):
    """Jitted CHUNKED prefill step: (params, tokens (B, T), cache,
    offset) -> (logits (B, T, V), cache) — processes a T-token chunk at
    any global ``offset``, attending causally within the chunk and
    fully to everything already cached below it. One compiled program
    per chunk length serves a whole streaming prefill:

    >>> extend = make_extend(cfg, mesh)
    >>> for i in range(0, Tp, C):
    ...     lg, cache = extend(params, prompt[:, i:i+C], cache, i)

    The caller keeps ``offset + T <= max_len`` (dynamic offsets cannot
    be trace-checked; out-of-range writes would clamp — see
    :func:`decode_step_dense`); a chunk longer than the cache errors at
    trace time. Equivalent position-for-position to one-shot
    ``make_prefill`` (the
    incremental forward is the training forward evaluated causally —
    tests/test_decode.py pins the chunked == one-shot == dense-oracle
    chain). The chunk attends through the masked cached-attention path
    (offset 0 one-shot prefill keeps the flash chunk kernel); the
    MoE capacity caveat of :func:`prefill_dense` applies per chunk.

    The cache is deliberately NOT donated here: on the axon-tunneled
    bench TPU the multi-token-chunk program with a donated cache
    pytree dies with an opaque backend InvalidArgument at execution
    (measured round 4 — the T=1 donated decode step and the undonated
    T>1 program both run fine, so the aliasing of chunked
    dynamic-update-slice outputs onto donated inputs is the trigger).
    Chunked prefill runs once per prompt, so the extra cache copy is
    noise next to the chunk compute."""

    _check_decode_mesh(cfg, mesh)
    bax = decode_batch_axes(cfg)
    cspecs = cache_specs(cfg, quantize_kv=quantize_kv)
    use_kernel = _decode_kernel_enabled()  # same snapshot discipline

    def local(params, tokens, cache, offset):
        # the T-vs-cache half of the clamp guard is trace-time checkable
        # (offset is dynamic: the caller owns offset + T <= max_len,
        # as documented for decode_step_dense)
        _check_prefill_fits(tokens.shape[1], cache)
        logits, cache = _incremental_forward(
            params, tokens, cache, offset, cfg, prefill=False,
            kv_slice=make_kv_slice(cfg), tp_psum=True,
            decode_kernel=use_kernel,
        )
        return logits, cache

    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(
            param_specs(cfg, mesh), P(bax, None), cspecs, P(),
        ),
        out_specs=(P(bax, None, None), cspecs),
        # extend is chunked (T > 1) on every real path, but a T == 1
        # chunk with quantize_kv + the kernel toggle traces the int8
        # decode kernel like a decode step — same vma carve-out
        check_vma=not _decode_kernel_interpreted(cfg, quantize_kv, use_kernel),
    )
    return jax.jit(f)


def make_generate(cfg: TransformerConfig, mesh: Mesh, n_new: int,
                  max_len: int | None = None, *,
                  temperature: float = 0.0, top_k: int | None = None,
                  eos_id: int | None = None, quantize_kv: bool = False,
                  ring: bool = False):
    """Jitted sharded generation: ``gen(params, prompt (B, Tp)[, key])``
    -> (B, n_new) tokens. Prefill + a lax.scan of decode steps inside
    ONE shard_map program — zero host round trips between tokens.
    Greedy by default; ``temperature > 0`` samples (optionally top-k)
    and ``eos_id`` rows that finish keep emitting the EOS token
    (static shapes; strip host-side). The returned callable takes the
    PRNG key as its third argument
    (replicated across the mesh — every tp member draws the same token
    from the identical post-psum logits; the dense and sharded
    programs produce the same stream for the same key).

    The attention inside every layer of the training forward is
    replaced by cache reads; the tp psum of the training path is
    implicit here because each device holds its q-head slice and the
    out-projection partial-sums are psummed per layer exactly like
    ``_forward_local`` — see ``_incremental_layer`` (attention output
    enters the residual after the wo einsum, whose head-shard partial
    sums cross tp via the psum below).

    ``ring=True`` (see :func:`make_ring_generate`) swaps the decode
    scan's cache carry for the O(W) sliding-window ring; ``max_len``
    is then ignored (the ring has no horizon).
    """

    _check_decode_mesh(cfg, mesh)
    W = _check_ring_cfg(cfg) if ring else None
    bax = decode_batch_axes(cfg)
    if n_new < 1:
        raise ValueError(f"n_new must be >= 1, got {n_new}")
    _check_sampling_params(temperature, top_k)
    use_kernel = _decode_kernel_enabled()  # make-time snapshot

    def local(params, prompt, key):
        B, Tp = prompt.shape
        # resolve the tri-state snapshot at THIS shard's batch (auto
        # routes the kernel only when the call serves enough rows to
        # amortize the scan boundary cost — see _route_kernel)
        routed = (
            _kernel_possible(cfg, quantize_kv, use_kernel)
            and _route_kernel(use_kernel, B)
        )
        if ring:
            L = Tp  # transient positional prefill cache, gathered below
        else:
            L = max_len if max_len is not None else Tp + n_new
            if L < Tp + n_new:
                raise ValueError(
                    f"max_len {L} < prompt {Tp} + n_new {n_new}: decode "
                    "positions would clamp into the last cache slot"
                )
            if quantize_kv and routed and L > 2048:
                # round up so the int8 decode KERNEL always has a big
                # lane-aligned block divisor (extra slots are masked).
                # Gated on the resolved routing: the einsum path needs
                # no alignment, and the extra masked positions would
                # skew its memory/time against the bf16 baseline
                L = -(-L // 2048) * 2048
        Hc = _cache_heads_global(cfg, mesh)
        tp = mesh.shape["tp"]
        cache = [
            _zero_cache_layer(B, L, Hc // tp, cfg.head_dim, cfg.dtype,
                              quantize_kv)
            for _ in range(cfg.n_layers)
        ]
        kv_slice = make_kv_slice(cfg)
        if ring and quantize_kv:
            # oracle alignment, same as _dense_runner: quantized ring
            # prefill attends the already-quantized cache — the only
            # math the scheduler's chunked admission can evaluate
            logits, cache = _aligned_quantized_prefill(
                params, prompt, cache, cfg, decode_kernel=routed,
                kv_slice=kv_slice, tp_psum=True,
            )
        else:
            logits, cache = _incremental_forward(
                params, prompt, cache, jnp.int32(0), cfg, prefill=True,
                kv_slice=kv_slice, tp_psum=True,
            )
        if ring:
            cache = [_ring_from_cache(cl, Tp, W) for cl in cache]
        # global batch-row offset of this shard, derived from the one
        # source of truth for the batch layout (dp-major, then ep)
        row0 = jnp.int32(0)
        for ax in decode_batch_axes(cfg):
            row0 = row0 * jax.lax.axis_size(ax) + jax.lax.axis_index(ax)
        row0 = row0 * B
        tok = _pick_token(
            logits[:, -1], Tp - 1, key, temperature, top_k,
            prompt.dtype, row0,
        )
        # all-False, derived from tok so it inherits tok's varying mesh
        # axes (a plain zeros carry trips the scan's vma type check)
        done = tok < jnp.asarray(0, tok.dtype)

        def step(carry, pos):
            tok, done, cache = carry
            lg, cache = _incremental_forward(
                params, tok[:, None], cache, pos, cfg, prefill=False,
                kv_slice=kv_slice, tp_psum=True, ring=ring,
                decode_kernel=routed,
            )
            nxt = _pick_token(
                lg[:, 0], pos, key, temperature, top_k, tok.dtype, row0
            )
            nxt, done = _eos_clamp(nxt, tok, done, eos_id)
            return (nxt, done, cache), tok

        # n_new - 1 decode forwards, as in the dense runner: the final
        # token comes out of the carry, not a discarded extra forward
        (tok, _, _), toks = jax.lax.scan(
            step, (tok, done, cache), Tp + jnp.arange(n_new - 1)
        )
        toks = jnp.concatenate([toks, tok[None]], axis=0)
        return toks.swapaxes(0, 1)

    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs(cfg, mesh), P(bax, None), P()),
        out_specs=P(bax, None),
        # the generate program can trace BOTH interpreted Pallas
        # kernels: flash in the prefill chunk, the int8 decode kernel
        # in the scan steps — either needs the vma carve-out
        check_vma=not (
            _flash_interpreted(cfg.attn_impl)
            or _decode_kernel_interpreted(cfg, quantize_kv, use_kernel)
        ),
    )
    jitted = jax.jit(f)

    def gen(params, prompt, key=None):
        _check_sampling(temperature, top_k, key)
        if key is None:
            key = jax.random.key(0)  # unused at temperature 0
        return jitted(params, prompt, key)

    return gen


def make_ring_generate(cfg: TransformerConfig, mesh: Mesh, n_new: int, *,
                       temperature: float = 0.0, top_k: int | None = None,
                       eos_id: int | None = None,
                       quantize_kv: bool = False):
    """Sharded sliding-window generation over the O(W) ring cache:
    ``gen(params, prompt (B, Tp)[, key])`` -> (B, n_new) tokens.

    The :func:`make_generate` program with the decode scan's cache carry
    replaced by the ring (see the module docstring): prefill runs
    positionally into a Tp-length transient (the chunk flash kernel
    applies the window band), each layer's last-W K/V gathers into ring
    slots, and every decode step writes slot ``pos % W`` and reads W
    positions — per-token cache traffic and carried HBM are O(W)
    however long the prompt or the stream. Sharding is unchanged:
    batch over dp (and ep for MoE), cache heads over tp."""
    return make_generate(
        cfg, mesh, n_new, temperature=temperature, top_k=top_k,
        eos_id=eos_id, quantize_kv=quantize_kv, ring=True,
    )
