"""Inference for the flagship transformer: KV cache, prefill, decode.

The reference has no inference code of any kind (it has no model code —
SURVEY §2); this is north-star flagship scope (VERDICT r3 missing #2):
a framework that trains long-context models must also serve them.

Design (TPU-first):

* **One incremental forward.** Prefill and decode are the same program
  at different chunk sizes: a chunk of ``T`` tokens at global offset
  ``off`` writes its per-layer K/V into the cache at ``[off, off+T)``
  and attends causally. Prefill (``off == 0``) needs no cache reads, so
  it runs the configured chunk kernel — the flash Pallas kernel for
  long prompts. Decode (``T == 1``) attends the single query against
  the whole cache through the grouped GQA einsums
  (:func:`~..parallel.ring_attention._group_scores`), so MQA/GQA
  configs read ``kv_heads`` cache heads, not ``n_heads`` — the KV
  bandwidth/memory win is structural, never faked by a repeat.
* **Static shapes.** The cache is ``(B, max_len, kv_heads, head_dim)``
  per layer; validity is positional masking (``kpos <= qpos``), never a
  dynamic slice length — one compiled program serves every step.
* **tp-sharded cache.** Cache heads shard over ``tp`` like the K/V
  projections. When ``kv_heads < tp`` (MQA/GQA serving with wide tp)
  the cache uses the *replicated-groups* layout: global head axis
  ``tp`` slots, slot ``t`` holding kv head ``t * kv_heads // tp`` —
  each device computes its own replica from the tp-replicated K/V
  projections, so the layout needs no extra collectives.
* **Sliding windows are masked, not yet rolled.** With
  ``attn_window=W`` the decode path masks the (q-W, q] band exactly
  like training, but the cache stays ``max_len`` long and every step
  still scores the full cache — an O(W) ring-buffer cache (the
  window's memory/bandwidth prize at W << max_len) is the natural
  next rung and changes only this module's cache layout.
* **Greedy generation is one program.** ``make_generate`` runs prefill
  plus a ``lax.scan`` over decode steps *inside a single shard_map
  jit* — no host round trip per token; on the tunneled bench chip that
  is the difference between ~110 ms/token of fence RTT and pure
  device-side stepping.

Decode-time attention is exact; the teacher-forced logits equal the
training forward's (tests/test_decode.py pins both, sharded included).
One caveat: MoE expert capacity is a per-call shape, so MoE configs
tight enough to drop tokens route per chunk, not per full sequence —
see :func:`prefill_dense`.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.ring_attention import (
    _band_mask,
    _flash_interpreted,
    _group_pv,
    _group_scores,
    resolve_attention_impl,
)
from .moe import moe_ffn_dense, moe_ffn_sharded
from .transformer import (
    TransformerConfig,
    _kv_tp_sharded,
    _ln,
    _mlp,
    _rope,
    make_kv_slice,
    param_specs,
)

__all__ = [
    "init_cache",
    "cache_specs",
    "decode_batch_axes",
    "prefill_dense",
    "decode_step_dense",
    "generate_dense",
    "make_generate",
    "make_prefill",
    "make_decode_step",
    "make_extend",
]

_NEG = -1e30  # matches parallel/ring_attention.py


def _cache_heads_global(cfg: TransformerConfig, mesh: Mesh | None) -> int:
    """Global cache head count: ``kv_heads``, or ``tp`` replicated-group
    slots when kv_heads < tp (see module docstring)."""
    if mesh is None or "tp" not in mesh.axis_names:
        return cfg.kv_heads
    tp = mesh.shape["tp"]
    return cfg.kv_heads if _kv_tp_sharded(cfg, mesh) else tp


def init_cache(
    cfg: TransformerConfig, batch: int, max_len: int,
    mesh: Mesh | None = None,
) -> list[dict]:
    """Zeroed per-layer KV cache (host pytree; ``shard_cache`` places
    it). Layout: layers -> {"k","v"} of (B, max_len, cache_heads, Dh)."""
    H = _cache_heads_global(cfg, mesh)
    z = jnp.zeros((batch, max_len, H, cfg.head_dim), cfg.dtype)
    return [{"k": z, "v": z} for _ in range(cfg.n_layers)]


def decode_batch_axes(cfg: TransformerConfig) -> tuple[str, ...]:
    """Mesh axes the batch shards over at decode: MoE configs add
    ``ep`` (every expert-parallel member routes distinct rows — the
    GShard layout, matching the training path's ``batch_axes``)."""
    return ("dp", "ep") if cfg.n_experts else ("dp",)


def cache_specs(cfg: TransformerConfig) -> list[dict]:
    """PartitionSpecs for the cache: batch over dp (and ep for MoE),
    heads over tp."""
    s = P(decode_batch_axes(cfg), None, "tp", None)
    return [{"k": s, "v": s} for _ in range(cfg.n_layers)]


def shard_cache(cache, cfg: TransformerConfig, mesh: Mesh):
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        cache, cache_specs(cfg),
    )


def _cached_attention(q, kc, vc, qpos, scale, window=None):
    """Grouped attention of the chunk's queries against the full cache.

    q: (B, T, H, D); kc/vc: (B, Lmax, Hkv, D) with positions
    ``arange(Lmax)``; validity is ``kpos <= qpos`` (cache entries past
    the chunk are zeros AND masked; entries below the offset are real),
    intersected with the sliding-window band when ``window`` is set.
    """
    Lmax = kc.shape[1]
    s = _group_scores(q, kc, scale)  # (B, H, T, Lmax) f32
    # the one band predicate (parallel/ring_attention._band_mask): the
    # serving path cannot silently diverge from the training oracle
    mask = _band_mask(qpos, jnp.arange(Lmax), True, window)
    s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    o = _group_pv(p, vc)  # (B, T, H, D) f32
    return o.astype(q.dtype)


def _incremental_layer(x, lp, cache_l, qpos, cfg, *, chunk_attn, kv_slice,
                       tp_psum):
    """One layer of the incremental forward: write the chunk's K/V into
    the cache at ``qpos`` positions, attend, MLP. Returns (x, cache_l).
    ``tp_psum=True`` combines the head-shard out-projection and the
    d_ff-shard down-projection over the ``tp`` axis, exactly like the
    training path (models/transformer.py ``_forward_local``)."""
    h = _ln(x, lp["ln1_s"], lp["ln1_b"])
    q = jnp.einsum("bld,dhk->blhk", h, lp["wq"])
    k = jnp.einsum("bld,dhk->blhk", h, lp["wk"])
    v = jnp.einsum("bld,dhk->blhk", h, lp["wv"])
    if kv_slice is not None:
        k, v = kv_slice(k), kv_slice(v)
    q, k = _rope(q, qpos), _rope(k, qpos)
    off = qpos[0]
    kc = jax.lax.dynamic_update_slice_in_dim(cache_l["k"], k, off, axis=1)
    vc = jax.lax.dynamic_update_slice_in_dim(cache_l["v"], v, off, axis=1)
    scale = cfg.head_dim ** -0.5
    if chunk_attn is not None:
        # prefill at offset 0: attention lives entirely inside the chunk,
        # so the configured chunk kernel (flash on TPU) does the work
        o = chunk_attn(q, k, v)
    else:
        o = _cached_attention(q, kc, vc, qpos, scale, cfg.attn_window)
    attn_out = jnp.einsum("blhk,hkd->bld", o, lp["wo"])
    if tp_psum:
        attn_out = jax.lax.psum(attn_out, "tp")
    x = x + attn_out
    h2 = _ln(x, lp["ln2_s"], lp["ln2_b"])
    if cfg.n_experts:
        if tp_psum:
            # inside the mesh program: expert-parallel routing, exactly
            # the training path's MoE branch (_forward_local) — experts
            # over ep via all_to_all, hidden dims over tp
            y, ybias, _ = moe_ffn_sharded(h2, lp, cfg.capacity_factor)
            x = x + jax.lax.psum(y, "tp") + ybias
        else:
            x = x + moe_ffn_dense(h2, lp, cfg.capacity_factor)[0]
    else:
        y = _mlp(h2, lp)
        if tp_psum:
            y = jax.lax.psum(y, "tp")
        x = x + y + lp["b2"]
    return x, {"k": kc, "v": vc}


def _incremental_forward(params, tokens, cache, offset, cfg,
                         *, prefill, kv_slice=None, tp_psum=False):
    """Chunk forward at global ``offset``; returns (logits, cache).

    ``prefill=True`` (static) means offset is known to be 0 and chunk
    attention uses the configured kernel; otherwise attention runs
    against the cache.
    """
    T = tokens.shape[1]
    qpos = offset + jnp.arange(T)
    chunk_attn = None
    if prefill:
        chunk_attn = partial(
            resolve_attention_impl(cfg.attn_impl), causal=True,
            window=cfg.attn_window,
        )
    x = params["emb"][tokens]
    new_cache = []
    for lp, cache_l in zip(params["layers"], cache):
        x, cache_l = _incremental_layer(
            x, lp, cache_l, qpos, cfg,
            chunk_attn=chunk_attn, kv_slice=kv_slice, tp_psum=tp_psum,
        )
        new_cache.append(cache_l)
    x = _ln(x, params["lnf_s"], params["lnf_b"])
    logits = jnp.einsum("bld,vd->blv", x, params["emb"])
    return logits, new_cache


# --------------------------------------------------------------------------
# dense (single-device oracle) API
# --------------------------------------------------------------------------


def _check_prefill_fits(T: int, cache) -> None:
    """Trace-time guard: ``dynamic_update_slice`` CLAMPS out-of-range
    offsets, so an over-long chunk would silently wrap the tail of the
    cache instead of erroring."""
    Lmax = jax.tree.leaves(cache)[0].shape[1]
    if T > Lmax:
        raise ValueError(
            f"chunk of {T} tokens does not fit the cache (max_len "
            f"{Lmax}); build the cache at least prompt+decode long"
        )


def prefill_dense(params, tokens, cache, cfg: TransformerConfig):
    """Fill the cache from a prompt; returns (logits (B, T, V), cache).

    MoE caveat: expert *capacity* is a per-call shape (ceil of
    tokens-routed-per-expert x capacity_factor, models/moe.py), so a
    config tight enough to DROP tokens can drop differently here than
    in the full-sequence training forward — teacher-forced equality
    holds exactly whenever no drops occur (generous capacity_factor or
    single-step decode, where capacity >= 1 covers every token)."""
    _check_prefill_fits(tokens.shape[1], cache)
    return _incremental_forward(
        params, tokens, cache, jnp.int32(0), cfg, prefill=True
    )


def decode_step_dense(params, token, cache, pos, cfg: TransformerConfig):
    """One decode step: ``token`` (B,) at global position ``pos``
    (scalar; caller keeps pos < the cache's max_len — out-of-range
    writes clamp, they do not error). Returns (logits (B, V), cache)."""
    logits, cache = _incremental_forward(
        params, token[:, None], cache, pos, cfg, prefill=False
    )
    return logits[:, 0], cache


def _pick_token(logits, pos, key, temperature, top_k, dtype, row0=0):
    """Next-token choice shared by the dense and sharded generators:
    greedy at ``temperature == 0`` (static), else softmax sampling at
    the given temperature, optionally truncated to the top-k logits.

    The per-draw key folds the global position AND the GLOBAL batch
    row (``row0`` = this shard's batch offset under shard_map, the
    mixed-radix index over ``decode_batch_axes`` times B_local): a
    fixed key then yields one stream per
    (row, position) regardless of how the batch is sharded — dense and
    dp-sharded programs sample identical tokens, and every tp member
    draws the same token from the identical post-psum logits."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(dtype)
    lg = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        # lax.top_k's partial reduction, NOT a full-vocab sort: this
        # runs per token inside the latency-critical decode scan
        kth = jax.lax.top_k(lg, int(top_k))[0][..., -1:]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    kpos = jax.random.fold_in(key, pos)
    rows = row0 + jnp.arange(lg.shape[0])
    return jax.vmap(
        lambda r, ll: jax.random.categorical(
            jax.random.fold_in(kpos, r), ll
        )
    )(rows, lg).astype(dtype)


def _check_sampling_params(temperature, top_k) -> None:
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")


def _check_sampling(temperature, top_k, key) -> None:
    _check_sampling_params(temperature, top_k)
    if temperature == 0.0 and key is not None:
        raise ValueError("a PRNG key is only meaningful with "
                         "temperature > 0 (greedy decoding is "
                         "deterministic)")
    if temperature > 0.0 and key is None:
        raise ValueError("temperature > 0 needs a jax.random key")


def _eos_clamp(nxt, tok, done, eos_id):
    """Static-shape EOS handling: once a row has emitted ``eos_id``
    every later token is forced to it (the scan always runs n_new
    steps — shapes never depend on content; callers strip the EOS tail
    host-side). Returns (next_token, next_done)."""
    if eos_id is None:
        return nxt, done
    done = jnp.logical_or(done, tok == eos_id)
    return jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt), done


@functools.lru_cache(maxsize=64)
def _dense_runner(cfg: TransformerConfig, B: int, Tp: int, n_new: int,
                  max_len: int, temperature: float, top_k: int | None,
                  eos_id: int | None):
    """Shape-keyed jitted prefill+scan generation program (one compile
    per (cfg, shapes, sampling); the cache is built inside the jit, not
    baked in as a constant)."""

    @jax.jit
    def run(params, prompt, key):
        c = init_cache(cfg, B, max_len)
        logits, c = prefill_dense(params, prompt, c, cfg)
        tok = _pick_token(
            logits[:, -1], Tp - 1, key, temperature, top_k, prompt.dtype
        )
        done = jnp.zeros((B,), bool)

        def step(carry, pos):
            tok, done, c = carry
            lg, c = decode_step_dense(params, tok, c, pos, cfg)
            nxt = _pick_token(lg, pos, key, temperature, top_k, tok.dtype)
            nxt, done = _eos_clamp(nxt, tok, done, eos_id)
            return (nxt, done, c), tok

        # n_new - 1 decode forwards: the last emitted token is the final
        # carry, so no forward is spent computing a discarded successor
        (tok, _, _), toks = jax.lax.scan(
            step, (tok, done, c), Tp + jnp.arange(n_new - 1)
        )
        toks = jnp.concatenate([toks, tok[None]], axis=0)
        return toks.swapaxes(0, 1)  # (B, n_new)

    return run


def generate_dense(params, prompt, n_new: int, cfg: TransformerConfig,
                   max_len: int | None = None, *,
                   temperature: float = 0.0, top_k: int | None = None,
                   key=None, eos_id: int | None = None):
    """Generation, dense single-program: prefill + lax.scan of decode
    steps under one jit (compiled once per shape, cached). Greedy by
    default; ``temperature > 0`` samples (optionally top-k-truncated)
    with the given ``key``. ``eos_id``: rows that emit it keep emitting
    it (static shapes; strip the tail host-side). Returns (B, n_new)
    tokens."""
    if n_new < 1:
        raise ValueError(f"n_new must be >= 1, got {n_new}")
    _check_sampling(temperature, top_k, key)
    B, Tp = prompt.shape
    if max_len is None:
        max_len = Tp + n_new
    if max_len < Tp + n_new:
        raise ValueError(
            f"max_len {max_len} < prompt {Tp} + n_new {n_new}: decode "
            "positions would clamp into the last cache slot"
        )
    if key is None:
        key = jax.random.key(0)  # unused at temperature 0
    return _dense_runner(
        cfg, B, Tp, n_new, max_len, float(temperature), top_k, eos_id
    )(params, prompt, key)


# --------------------------------------------------------------------------
# sharded (dp [x ep] x tp mesh) API
# --------------------------------------------------------------------------


def _check_decode_mesh(cfg: TransformerConfig, mesh: Mesh):
    """MoE decode composes expert parallelism: the mesh must carry an
    ``ep`` axis (size 1 folds experts onto each member) alongside dp
    and tp — same layout as the training path."""
    need = {"dp", "tp"} | ({"ep"} if cfg.n_experts else set())
    missing = need - set(mesh.axis_names)
    if missing:
        raise ValueError(
            f"decode mesh is missing axes {sorted(missing)}; MoE "
            "configs shard over (dp, ep, tp), dense over (dp, tp)"
        )


def make_prefill(cfg: TransformerConfig, mesh: Mesh):
    """Jitted sharded prefill: (params, tokens (B, Tp), cache) ->
    (last-position logits (B, V), cache). Batch over dp (and ep for
    MoE — expert routing runs sharded, all_to_all over ep, exactly as
    in training), heads over tp."""
    _check_decode_mesh(cfg, mesh)
    bax = decode_batch_axes(cfg)

    def local(params, tokens, cache):
        _check_prefill_fits(tokens.shape[1], cache)
        logits, cache = _incremental_forward(
            params, tokens, cache, jnp.int32(0), cfg, prefill=True,
            kv_slice=make_kv_slice(cfg), tp_psum=True,
        )
        return logits[:, -1], cache

    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs(cfg, mesh), P(bax, None), cache_specs(cfg)),
        out_specs=(P(bax, None), cache_specs(cfg)),
        check_vma=not _flash_interpreted(cfg.attn_impl),
    )
    return jax.jit(f)


def make_decode_step(cfg: TransformerConfig, mesh: Mesh):
    """Jitted sharded decode step: (params, token (B,), cache, pos) ->
    (logits (B, V), cache). Donates the cache for in-place HBM update.
    """

    _check_decode_mesh(cfg, mesh)
    bax = decode_batch_axes(cfg)

    def local(params, token, cache, pos):
        logits, cache = _incremental_forward(
            params, token[:, None], cache, pos, cfg, prefill=False,
            kv_slice=make_kv_slice(cfg), tp_psum=True,
        )
        return logits[:, 0], cache

    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(
            param_specs(cfg, mesh), P(bax), cache_specs(cfg), P(),
        ),
        out_specs=(P(bax, None), cache_specs(cfg)),
        # decode traces NO flash kernel (masked cached attention), so
        # the interpreted-Pallas vma carve-out does not apply — keep
        # shard_map's varying-axes checking on
        check_vma=True,
    )
    return jax.jit(f, donate_argnums=(2,))


def make_extend(cfg: TransformerConfig, mesh: Mesh):
    """Jitted CHUNKED prefill step: (params, tokens (B, T), cache,
    offset) -> (logits (B, T, V), cache) — processes a T-token chunk at
    any global ``offset``, attending causally within the chunk and
    fully to everything already cached below it. One compiled program
    per chunk length serves a whole streaming prefill:

    >>> extend = make_extend(cfg, mesh)
    >>> for i in range(0, Tp, C):
    ...     lg, cache = extend(params, prompt[:, i:i+C], cache, i)

    The caller keeps ``offset + T <= max_len`` (dynamic offsets cannot
    be trace-checked; out-of-range writes would clamp — see
    :func:`decode_step_dense`); a chunk longer than the cache errors at
    trace time. Equivalent position-for-position to one-shot
    ``make_prefill`` (the
    incremental forward is the training forward evaluated causally —
    tests/test_decode.py pins the chunked == one-shot == dense-oracle
    chain). The chunk attends through the masked cached-attention path
    (offset 0 one-shot prefill keeps the flash chunk kernel); the
    MoE capacity caveat of :func:`prefill_dense` applies per chunk.

    The cache is deliberately NOT donated here: on the axon-tunneled
    bench TPU the multi-token-chunk program with a donated cache
    pytree dies with an opaque backend InvalidArgument at execution
    (measured round 4 — the T=1 donated decode step and the undonated
    T>1 program both run fine, so the aliasing of chunked
    dynamic-update-slice outputs onto donated inputs is the trigger).
    Chunked prefill runs once per prompt, so the extra cache copy is
    noise next to the chunk compute."""

    _check_decode_mesh(cfg, mesh)
    bax = decode_batch_axes(cfg)

    def local(params, tokens, cache, offset):
        # the T-vs-cache half of the clamp guard is trace-time checkable
        # (offset is dynamic: the caller owns offset + T <= max_len,
        # as documented for decode_step_dense)
        _check_prefill_fits(tokens.shape[1], cache)
        logits, cache = _incremental_forward(
            params, tokens, cache, offset, cfg, prefill=False,
            kv_slice=make_kv_slice(cfg), tp_psum=True,
        )
        return logits, cache

    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(
            param_specs(cfg, mesh), P(bax, None), cache_specs(cfg), P(),
        ),
        out_specs=(P(bax, None, None), cache_specs(cfg)),
        check_vma=True,  # no flash kernel in the extend program
    )
    return jax.jit(f)


def make_generate(cfg: TransformerConfig, mesh: Mesh, n_new: int,
                  max_len: int | None = None, *,
                  temperature: float = 0.0, top_k: int | None = None,
                  eos_id: int | None = None):
    """Jitted sharded generation: ``gen(params, prompt (B, Tp)[, key])``
    -> (B, n_new) tokens. Prefill + a lax.scan of decode steps inside
    ONE shard_map program — zero host round trips between tokens.
    Greedy by default; ``temperature > 0`` samples (optionally top-k)
    and ``eos_id`` rows that finish keep emitting the EOS token
    (static shapes; strip host-side). The returned callable takes the
    PRNG key as its third argument
    (replicated across the mesh — every tp member draws the same token
    from the identical post-psum logits; the dense and sharded
    programs produce the same stream for the same key).

    The attention inside every layer of the training forward is
    replaced by cache reads; the tp psum of the training path is
    implicit here because each device holds its q-head slice and the
    out-projection partial-sums are psummed per layer exactly like
    ``_forward_local`` — see ``_incremental_layer`` (attention output
    enters the residual after the wo einsum, whose head-shard partial
    sums cross tp via the psum below).
    """

    _check_decode_mesh(cfg, mesh)
    bax = decode_batch_axes(cfg)
    if n_new < 1:
        raise ValueError(f"n_new must be >= 1, got {n_new}")
    _check_sampling_params(temperature, top_k)

    def local(params, prompt, key):
        B, Tp = prompt.shape
        L = max_len if max_len is not None else Tp + n_new
        if L < Tp + n_new:
            raise ValueError(
                f"max_len {L} < prompt {Tp} + n_new {n_new}: decode "
                "positions would clamp into the last cache slot"
            )
        Hc = _cache_heads_global(cfg, mesh)
        tp = mesh.shape["tp"]
        cache = [
            {
                "k": jnp.zeros((B, L, Hc // tp, cfg.head_dim), cfg.dtype),
                "v": jnp.zeros((B, L, Hc // tp, cfg.head_dim), cfg.dtype),
            }
            for _ in range(cfg.n_layers)
        ]
        kv_slice = make_kv_slice(cfg)
        logits, cache = _incremental_forward(
            params, prompt, cache, jnp.int32(0), cfg, prefill=True,
            kv_slice=kv_slice, tp_psum=True,
        )
        # global batch-row offset of this shard, derived from the one
        # source of truth for the batch layout (dp-major, then ep)
        row0 = jnp.int32(0)
        for ax in decode_batch_axes(cfg):
            row0 = row0 * jax.lax.axis_size(ax) + jax.lax.axis_index(ax)
        row0 = row0 * B
        tok = _pick_token(
            logits[:, -1], Tp - 1, key, temperature, top_k,
            prompt.dtype, row0,
        )
        # all-False, derived from tok so it inherits tok's varying mesh
        # axes (a plain zeros carry trips the scan's vma type check)
        done = tok < jnp.asarray(0, tok.dtype)

        def step(carry, pos):
            tok, done, cache = carry
            lg, cache = _incremental_forward(
                params, tok[:, None], cache, pos, cfg, prefill=False,
                kv_slice=kv_slice, tp_psum=True,
            )
            nxt = _pick_token(
                lg[:, 0], pos, key, temperature, top_k, tok.dtype, row0
            )
            nxt, done = _eos_clamp(nxt, tok, done, eos_id)
            return (nxt, done, cache), tok

        # n_new - 1 decode forwards, as in the dense runner: the final
        # token comes out of the carry, not a discarded extra forward
        (tok, _, _), toks = jax.lax.scan(
            step, (tok, done, cache), Tp + jnp.arange(n_new - 1)
        )
        toks = jnp.concatenate([toks, tok[None]], axis=0)
        return toks.swapaxes(0, 1)

    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs(cfg, mesh), P(bax, None), P()),
        out_specs=P(bax, None),
        check_vma=not _flash_interpreted(cfg.attn_impl),
    )
    jitted = jax.jit(f)

    def gen(params, prompt, key=None):
        _check_sampling(temperature, top_k, key)
        if key is None:
            key = jax.random.key(0)  # unused at temperature 0
        return jitted(params, prompt, key)

    return gen
