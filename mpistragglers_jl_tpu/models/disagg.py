"""Disaggregated prefill/decode serving: live KV-page migration.

A unified fleet makes compute-bound, bursty PREFILL and memory-
bandwidth-bound, steady DECODE contend for the same chips: one
long-prompt burst inflates every tick it shares a scheduler with, and
decode p99 — the inter-token latency users feel — collapses (ROADMAP
item 1; docs/PERF.md round 16 prices it). This module splits the
serving tier in two and moves a request's KV state between the tiers as
a portable page-layout transfer, in the spirit of memory-efficient
array redistribution (arXiv 2112.01075): plan the layout, move pages,
never materialize an intermediate.

Three layers, bottom-up:

* **Scheduler hooks** (models/serving.py): ``export_page_state`` pulls
  one decoding slot's page set out of the pool as a ``(1, W, ...)``
  ring view per layer (fresh device buffers) plus the row's
  token/position/PRNG-key state, freeing the slot;
  ``adopt_page_state`` re-plans the page budget in the destination
  pool — sharing resident prefix-digest pages with COW reservations
  exactly like admission and RE-REGISTERING the request's own chain,
  so copy-on-write sharing survives the move — then scatters the view
  through the new table. A migrated stream equals the never-migrated
  oracle token-for-token (tests/test_disagg.py pins it across fp/int8,
  COW-shared prefixes, and every decode step offset).
* **The planner** (:class:`MigrationPlanner`): owns the window where a
  request is resident NOWHERE — capture on the source, completion on
  the destination, and the cancellation contract in between (a
  ``cancel()`` arriving mid-migration releases planner-held frames and
  any partial destination adoption, never double-frees). The
  in-process fast path hands the captured device arrays straight to
  the destination scatter (no host serialization); cross-process,
  :func:`ticket_to_frames` serializes the page payload into ring-sized
  transfer frames over a :class:`MigrationRing` — the
  ``native/rings.py`` pin-count discipline end-to-end (slots stay
  pinned while any consumer view lives; an all-pinned ring falls back
  to copying frames, never waits).
* **Tier wrappers** (:class:`PrefillWorker` / :class:`DecodeReplica`):
  scheduler-shaped replicas (the router protocol) tagged with a
  ``tier`` attribute and the migration verbs ``migrate_out`` /
  ``can_adopt`` / ``adopt`` / ``migration_nbytes``. A
  :class:`~.router.RequestRouter` with ``policy="two_tier"`` is the
  placement brain: fresh requests land on the prefill tier, streams
  past their first token migrate to the decode tier (subject to the
  migration-size threshold), and :func:`~..sim.tune.sweep_tier_split`
  prices the (n_prefill, n_decode) split and threshold offline on
  virtual time exactly the way router policies are swept.

Observability for the handoff plane (``disagg_*`` series, the
migration latency histogram, per-tier depth gauges, and the
flight-recorder instant event per handoff) lives in the router's
two-tier path — one counting point for live wrappers and sim replicas
alike; see models/router.py.
"""

from __future__ import annotations

import mmap as _mmap
from typing import Any

import numpy as np

from ..native.rings import MemfdRegion, RingAlloc, as_u8, track_release

__all__ = [
    "MigrationTicket",
    "MigrationPlanner",
    "MigrationRing",
    "MigrationRingReader",
    "PrefillWorker",
    "DecodeReplica",
    "ticket_to_frames",
    "ticket_from_frames",
    "page_to_frames",
    "page_from_frames",
]


# --------------------------------------------------------------------------
# tickets: the portable request image
# --------------------------------------------------------------------------


class MigrationTicket:
    """One captured request in flight between schedulers: the exported
    page state (models/serving.py ``export_page_state``), the byte/page
    accounting the router's threshold and the PERF byte model price,
    and the release contract — :meth:`release` drops every resource the
    ticket still holds (device arrays, ring-frame pins) and is
    idempotent, so cancel paths can never double-free."""

    __slots__ = ("state", "reason", "pages", "nbytes", "frames",
                 "_ring", "_released", "_owner", "trace")

    def __init__(self, state: dict, *, reason: str = "prefill_done"):
        self.state = state
        self.reason = reason
        self.pages = int(state["n_pages"])
        # bytes actually moved: the request's page set across every
        # layer and leaf (W rows are gathered, but only pages rows are
        # live content — the byte model prices pages, docs/PERF.md)
        per_page = 0
        for cl in state["ring"]:
            for a in cl.values():
                per_page += a.nbytes * state["P"] // a.shape[1]
        self.nbytes = self.pages * per_page
        self.frames: list[list] | None = None
        self._ring: "MigrationRing | None" = None
        self._released = False
        self._owner: "MigrationPlanner | None" = None
        # causal-trace id riding WITH the pages (round 22): set from
        # the captured request so the destination can rejoin a rebuilt
        # request to its trace after a frame-serialized hop
        self.trace = None

    @property
    def request(self):
        """The in-process request object (None when the ticket was
        rebuilt from frames — adoption constructs a fresh one)."""
        return self.state.get("request")

    def release(self) -> None:
        """Drop everything the ticket holds: the captured ring view
        (device buffers) and, when the payload was framed through a
        :class:`MigrationRing`, the sender-side slot pins. Idempotent —
        the mid-migration cancel path and post-adoption cleanup can
        both call it."""
        if self._released:
            return
        self._released = True
        self.state["ring"] = None
        if self.frames is not None and self._ring is not None:
            for seg in self.frames:
                self._ring.release_frames(seg)
        self.frames = None

    def __repr__(self) -> str:
        return (
            f"MigrationTicket({self.reason}, pages={self.pages}, "
            f"{self.nbytes / 1e6:.2f} MB"
            f"{', released' if self._released else ''})"
        )


# --------------------------------------------------------------------------
# ring-sized transfer frames (native/rings.py discipline)
# --------------------------------------------------------------------------


class SlotFrame:
    """One payload chunk resident in a migration-ring slot: the control
    marker that crosses to the consumer, who acks by letting its served
    views die (``track_release`` finalizers drop the pins)."""

    __slots__ = ("slot", "gen", "nbytes")

    def __init__(self, slot: int, gen: int, nbytes: int):
        self.slot = slot
        self.gen = gen
        self.nbytes = nbytes


class CopyFrame:
    """The all-pinned fallback: payload bytes carried in the control
    channel itself. Correctness never waits on a consumer's GC —
    rings.py's contract, inherited wholesale."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data


class MigrationRing:
    """Sender side of the cross-process migration transport: one memfd
    region divided into fixed slots, :class:`~..native.rings.RingAlloc`
    pin-counting slot lifetimes. ``fd`` is what crosses to the consumer
    once (SCM_RIGHTS on the native transport; inheritance in tests);
    payload bytes cross zero-copy — the consumer maps the same pages
    and reads frames in place. Where ``memfd_create`` is unavailable
    the ring degrades to all-:class:`CopyFrame` transport.

    Pin model: the sender holds one pin per in-flight
    :class:`SlotFrame` (dropped by :meth:`release_frames`, which
    :meth:`MigrationTicket.release` calls); each consumer view adds its
    own holder released by its ``track_release`` finalizer. A slot
    recycles only when both are gone; when every slot is pinned,
    :meth:`send_segment` falls back to copying frames and counts the
    stall."""

    def __init__(self, *, slot_bytes: int = 1 << 20, slots: int = 4,
                 name: str = "disagg-migrate"):
        if slot_bytes < 1 or slots < 1:
            raise ValueError("slot_bytes and slots must be >= 1")
        self.slot_bytes = int(slot_bytes)
        self.slots = int(slots)
        self.region = MemfdRegion.create(self.slots * self.slot_bytes,
                                         name)
        self.alloc = RingAlloc(self.slots)
        self.stalls = 0
        self.zero_copy_bytes = 0
        self.copied_bytes = 0

    @property
    def fd(self) -> int | None:
        return None if self.region is None else self.region.fd

    def send_segment(self, buf) -> list:
        """Stage one payload segment as a frame list: ring-slot frames
        while slots are free, copying frames when every slot is pinned
        (the stall counter records each fallback chunk)."""
        data = as_u8(buf)
        frames: list = []
        n = data.nbytes
        off = 0
        while True:
            take = min(self.slot_bytes, n - off)
            got = None
            if self.region is not None:
                got = self.alloc.acquire(("sender",))
            if got is None:
                if self.region is not None:
                    self.stalls += 1
                frames.append(
                    CopyFrame(data[off:off + take].tobytes())
                )
                self.copied_bytes += take
            else:
                slot, gen = got
                base = slot * self.slot_bytes
                self.region.view[base:base + take] = data[off:off + take]
                frames.append(SlotFrame(slot, gen, take))
                self.zero_copy_bytes += take
            off += take
            if off >= n:
                return frames

    def release_frames(self, frames: list) -> None:
        """Drop the SENDER pin of every slot frame (stale generations
        are ignored by the allocator, so a double release is a no-op).
        Consumer-view pins are untouched — those die with the views."""
        for f in frames:
            if isinstance(f, SlotFrame):
                self.alloc.release(f.slot, f.gen, "sender")

    @property
    def pinned(self) -> int:
        return self.alloc.pinned

    def close(self) -> None:
        if self.region is not None:
            self.region.close()
            self.region = None


class MigrationRingReader:
    """Consumer side: its OWN read-only mapping of the sender's region
    (in-process: built from the ring; cross-process: from the fd that
    crossed once). Frame payloads are served as ``memoryview``s of
    ``track_release``-registered views — the slot stays pinned exactly
    as long as any derived buffer lives, and a stale generation (the
    sender reclaimed and reused the slot before this read) is served as
    a copy rather than a torn view.

    ``add_holder`` / ``release`` default to the sender allocator's
    methods (in-process adoption, the tests); a cross-process consumer
    passes callables that ship ``(slot, gen, token)`` acks back over
    its control channel — the result-ring ack shape of
    native/transport.py."""

    def __init__(self, ring: MigrationRing | None = None, *,
                 fd: int | None = None, slots: int | None = None,
                 slot_bytes: int | None = None, add_holder=None,
                 release=None):
        if ring is not None:
            fd = ring.fd
            slots = ring.slots
            slot_bytes = ring.slot_bytes
            if add_holder is None:
                add_holder = ring.alloc.add_holder
            if release is None:
                release = ring.alloc.release
        self.slot_bytes = int(slot_bytes)
        self._add_holder = add_holder
        self._release = release
        self._n = 0
        if fd is None:
            self._mm = None
            self._view = None
        else:
            self._mm = _mmap.mmap(fd, int(slots) * self.slot_bytes,
                                  _mmap.MAP_SHARED, _mmap.PROT_READ)
            self._view = np.frombuffer(self._mm, np.uint8)

    def frame_payload(self, frame) -> memoryview:
        """One frame's bytes. Slot frames pin their slot for the
        view's lifetime; copy frames are already private bytes."""
        if isinstance(frame, CopyFrame):
            return memoryview(frame.data)
        base = frame.slot * self.slot_bytes
        if self._view is not None and self._add_holder is not None:
            token = ("view", self._n)
            self._n += 1
            if self._add_holder(frame.slot, frame.gen, token):
                v = self._view[base:base + frame.nbytes]
                track_release(v, self._release, frame.slot, frame.gen,
                              token)
                return memoryview(v)
        # stale generation or no ack channel: a private copy is the
        # only view that cannot tear
        return memoryview(
            bytes(self._view[base:base + frame.nbytes])
        )

    def read_segment(self, frames: list) -> np.ndarray:
        """Reassemble one segment as a flat uint8 array — zero-copy
        (memoryview-backed, slot pinned) when the segment fits one
        frame, a private copy when it was chunked."""
        views = [self.frame_payload(f) for f in frames]
        if len(views) == 1:
            return np.frombuffer(views[0], np.uint8)
        return np.frombuffer(b"".join(bytes(v) for v in views),
                             np.uint8)

    def close(self) -> None:
        self._view = None
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:  # served views alive; GC finishes
                pass
            self._mm = None


# --------------------------------------------------------------------------
# frame (de)serialization
# --------------------------------------------------------------------------


def page_to_frames(ring: MigrationRing, payload) -> list:
    """Stage ONE prefix page's KV bytes on the migration ring — the
    cache plane's T3 (peer-fetch) wire unit. A page is a single flat
    segment (the concatenated sorted-leaf row slices the serving
    scheduler's ``_page_payload`` produces), so it rides the same
    frames a ticket leaf does: slot frames while the ring has room,
    copying frames under pin pressure. The caller owns the sender
    pins until :func:`page_from_frames` (or ``release_frames``)."""
    return ring.send_segment(payload)


def page_from_frames(reader: MigrationRingReader, frames: list, *,
                     ring: MigrationRing | None = None) -> np.ndarray:
    """Read one page back off its frames as a flat uint8 array, then
    (when ``ring`` is given — the in-process adoption shape) drop the
    sender pins; consumer-view pins keep the bytes alive until the
    returned array dies, so the destination can device-scatter from
    it without a defensive copy."""
    out = reader.read_segment(frames)
    if ring is not None:
        ring.release_frames(frames)
    return out


def ticket_to_frames(ticket: MigrationTicket,
                     ring: MigrationRing) -> dict:
    """Serialize a ticket's page payload into ring-sized transfer
    frames: one segment per cache leaf (plus the prompt and PRNG-key
    segments), each staged through ``ring``. Returns the JSON-able
    meta dict; the frame lists land on ``ticket.frames`` (the ticket
    now holds the sender pins — :meth:`MigrationTicket.release` frees
    them). The meta + frames pair is everything the receiving process
    needs (:func:`ticket_from_frames`); shipping them is the caller's
    control channel's job."""
    if ticket.state.get("ring") is None:
        raise ValueError("ticket already released or framed")
    st = ticket.state
    segs: list[np.ndarray] = [
        np.ascontiguousarray(np.asarray(st["prompt"], np.int32)),
        np.ascontiguousarray(np.asarray(st["key_data"])),
    ]
    layers_meta = []
    for cl in st["ring"]:
        leaf_meta = []
        for kk in sorted(cl):
            a = np.asarray(cl[kk])
            leaf_meta.append([kk, list(a.shape), str(a.dtype)])
            segs.append(np.ascontiguousarray(a))
        layers_meta.append(leaf_meta)
    ticket.frames = [ring.send_segment(s) for s in segs]
    ticket._ring = ring
    st["ring"] = None  # the frames are the payload now
    meta = {
        "reason": ticket.reason,
        "tokens": list(st["tokens"]),
        "max_new": int(st["max_new"]),
        "tok": int(st["tok"]),
        "pos": int(st["pos"]),
        "digests": [d.hex() for d in st["digests"]],
        "n_cover": int(st["n_cover"]),
        "n_pages": int(st["n_pages"]),
        "P": int(st["P"]),
        "W": int(st["W"]),
        "quantize_kv": bool(st["quantize_kv"]),
        "temperature": float(st["temperature"]),
        "top_k": st["top_k"],
        "eos_id": st["eos_id"],
        "key_dtype": str(np.asarray(st["key_data"]).dtype),
        "layers": layers_meta,
    }
    return meta


def ticket_from_frames(meta: dict, frames: list[list],
                       reader: MigrationRingReader) -> MigrationTicket:
    """Rebuild a ticket on the consumer side: segments read through
    ``reader`` (zero-copy views where whole, the slots staying pinned
    until adoption's device copy consumed them), leaf arrays rewrapped
    at their recorded shapes/dtypes. The rebuilt ticket carries no
    request object — ``adopt`` constructs a fresh one."""
    it = iter(frames)
    # prompt and key state are copied out: they outlive adoption (the
    # rebuilt Request keeps its prompt for the stream's whole life, and
    # a zero-copy view there would pin its ring slot forever). The
    # LEAVES below stay zero-copy — they are the payload bulk and die
    # with the adoption scatter.
    prompt = np.frombuffer(
        reader.read_segment(next(it)), np.int32
    ).copy()
    key_data = np.frombuffer(
        reader.read_segment(next(it)), np.dtype(meta["key_dtype"])
    ).copy()
    ring = []
    for leaf_meta in meta["layers"]:
        cl = {}
        for kk, shape, dtype in leaf_meta:
            seg = reader.read_segment(next(it))
            cl[kk] = np.frombuffer(
                seg, np.dtype(dtype)
            ).reshape(shape)
        ring.append(cl)
    state = {
        "request": None,
        "prompt": prompt,
        "tokens": list(meta["tokens"]),
        "max_new": int(meta["max_new"]),
        "tok": int(meta["tok"]),
        "pos": int(meta["pos"]),
        "key_data": key_data,
        "ring": ring,
        "digests": tuple(bytes.fromhex(d) for d in meta["digests"]),
        "n_cover": int(meta["n_cover"]),
        "n_pages": int(meta["n_pages"]),
        "P": int(meta["P"]),
        "W": int(meta["W"]),
        "quantize_kv": bool(meta["quantize_kv"]),
        "temperature": float(meta["temperature"]),
        "top_k": meta["top_k"],
        "eos_id": meta["eos_id"],
    }
    return MigrationTicket(state, reason=meta.get("reason",
                                                  "prefill_done"))


# --------------------------------------------------------------------------
# the planner
# --------------------------------------------------------------------------


class MigrationPlanner:
    """Owns in-flight migrations: capture on the source scheduler,
    completion on the destination, and the cancel contract for the
    window in between, where the request is resident nowhere.

    The books are keyed on the captured request object's ``id`` (the
    scheduler-global request counter), so ``cancel(req)`` finds a
    mid-migration request no scheduler knows anymore — the losing-
    hedge-leg/cancelled-stream case the router relies on. Cancelling
    releases the ticket (device arrays, ring-frame pins) and marks the
    request cancelled; a ticket already landed is no longer here
    (completion removed it), so the destination's ordinary
    ``cancel()`` takes over and nothing double-frees — pinned by the
    drains-to-baseline tests in tests/test_disagg.py."""

    def __init__(self, *, ring: MigrationRing | None = None):
        self.ring = ring
        self._inflight: dict[int, MigrationTicket] = {}
        self.n_captured = 0
        self.n_landed = 0
        self.n_cancelled = 0

    def capture(self, src, req, *,
                reason: str = "prefill_done") -> MigrationTicket:
        """Export ``req`` from ``src`` (a paged scheduler or a tier
        wrapper) into a ticket; the source slot and pages are freed
        before this returns."""
        sched = getattr(src, "sched", src)
        state = sched.export_page_state(req)
        ticket = MigrationTicket(state, reason=reason)
        ticket.trace = getattr(req, "trace", None)
        ticket._owner = self
        self._inflight[req.id] = ticket
        self.n_captured += 1
        return ticket

    def complete(self, dst, ticket: MigrationTicket,
                 request=None) -> Any:
        """Land ``ticket`` on ``dst``; returns the continued request
        (the captured object in-process, a rebuilt one from frames).
        The ticket leaves the in-flight book first — a cancel racing
        this call either wins (the adopt below never runs: the ticket
        is released and raises) or loses (the book is empty, cancel
        falls through to the destination scheduler)."""
        if ticket._released:
            raise ValueError("cannot adopt a released ticket")
        sched = getattr(dst, "sched", dst)
        req = ticket.request
        # the in-flight entry lives on the planner that CAPTURED the
        # ticket (per-replica planners: the destination's planner may
        # be a different object — popping only our own book would leak
        # the owner's entry forever)
        owner = ticket._owner if ticket._owner is not None else self
        if req is not None:
            owner._inflight.pop(req.id, None)
        try:
            out = sched.adopt_page_state(ticket.state, request=request)
        except Exception:
            # adoption refused (capacity race, config mismatch): the
            # ticket is still in flight and must stay cancellable
            if req is not None:
                owner._inflight[req.id] = ticket
            raise
        self.n_landed += 1
        if ticket.trace is not None \
                and getattr(out, "trace", None) is None:
            # a request rebuilt from frames rejoins its trace here
            out.trace = ticket.trace
        ticket.state["request"] = out
        ticket.release()
        return out

    def cancel(self, req) -> bool:
        """Withdraw a request captured but not yet landed: release the
        ticket's resources and retire the request as cancelled.
        False when no migration of ``req`` is in flight here."""
        ticket = self._inflight.pop(getattr(req, "id", None), None)
        if ticket is None:
            return False
        ticket.release()
        req.finished = True
        req.reason = "cancelled"
        self.n_cancelled += 1
        return True

    @property
    def in_flight(self) -> int:
        return len(self._inflight)


# --------------------------------------------------------------------------
# tier wrappers (the router's replica protocol + migration verbs)
# --------------------------------------------------------------------------


class _TierReplica:
    """Shared half of the tier wrappers: a paged
    :class:`~.serving.ServingScheduler` plus a (shareable)
    :class:`MigrationPlanner`, delegating the whole replica protocol
    to the scheduler and adding the migration verbs the two-tier
    router drives. ``cancel`` covers all three residencies — the
    scheduler's books, then the planner's mid-migration window."""

    tier = "unified"

    def __init__(self, sched, *, planner: MigrationPlanner | None = None):
        if not getattr(sched, "paged", False):
            raise ValueError(
                f"{type(self).__name__} needs a paged scheduler "
                "(page_tokens=): migration is a page-layout transfer"
            )
        self.sched = sched
        self.planner = planner if planner is not None \
            else MigrationPlanner()

    # -- replica protocol (delegated) -----------------------------------
    def submit(self, prompt, max_new: int, key=None, trace=None):
        if trace is None:
            return self.sched.submit(prompt, max_new, key=key)
        return self.sched.submit(prompt, max_new, key=key,
                                 trace=trace)

    def step(self):
        return self.sched.step()

    def cancel(self, req) -> bool:
        return self.sched.cancel(req) or self.planner.cancel(req)

    @property
    def pending(self) -> int:
        return self.sched.pending

    @property
    def active(self) -> int:
        return self.sched.active

    def __getattr__(self, name):
        # pool/P/max_pages/paged/S/last_tick_at/...: the scheduler's
        # surface IS this replica's surface. __dict__ access keeps a
        # half-constructed instance an AttributeError, not recursion.
        sched = self.__dict__.get("sched")
        if sched is None:
            raise AttributeError(name)
        return getattr(sched, name)

    # -- migration verbs -------------------------------------------------
    def migration_nbytes(self, req) -> int:
        return self.sched.migration_nbytes(req)

    def migrate_out(self, req, *,
                    reason: str = "prefill_done") -> MigrationTicket:
        return self.planner.capture(self.sched, req, reason=reason)

    def can_adopt(self, ticket: MigrationTicket) -> bool:
        return (
            not ticket._released
            and self.sched.can_adopt_state(ticket.state)
        )

    def could_adopt(self, ticket: MigrationTicket) -> bool:
        """Could this replica EVER adopt ``ticket`` (page budget fits
        an empty pool, config compatible)? The router's park-vs-bounce
        signal — see :meth:`~.serving.ServingScheduler.could_adopt_state`."""
        return (
            not ticket._released
            and self.sched.could_adopt_state(ticket.state)
        )

    def adopt(self, ticket: MigrationTicket, request=None):
        return self.planner.complete(self.sched, ticket,
                                     request=request)

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}(active={self.active}, "
            f"pending={self.pending})"
        )


class PrefillWorker(_TierReplica):
    """The prefill tier: runs admission + chunked prefill into pages
    and hands streams off at their first token (``ready()`` lists
    them; the two-tier router drives ``migrate_out`` itself). Still a
    complete scheduler — requests under the migration-size threshold
    (or with no adoptable decode replica) simply keep decoding here,
    so the tier degrades gracefully instead of wedging."""

    tier = "prefill"

    def ready(self) -> list:
        """Requests past their first token and migratable right now —
        decoding slots, admission complete, stream unfinished."""
        sched = self.sched
        return [
            r for s, r in enumerate(sched._slot_req)
            if r is not None and s not in sched._admitting
            and r.tokens and not r.finished
        ]


class DecodeReplica(_TierReplica):
    """The decode tier: adopts migrated page sets (``adopt`` — pages
    landed via :class:`~.paging.PagePool` adoption, prefix chains
    re-registered) and runs the existing paged decode tick. Fresh
    submits still work (the router only sends them here when the
    prefill tier is gone — availability over purity)."""

    tier = "decode"
