"""Gradient-coded mini-batch SGD on logistic regression.

BASELINE config 5: logistic regression on synthetic data, gradient-coded
``asyncmap``, convergence vs wall-clock under injected stragglers. The
model is deliberately the simplest convex model with a dense gradient —
the point is the *training harness*: every epoch is one ``asyncmap`` call
with ``nwait = n - s``, and the update uses the gradient-code decoder
(ops/gradcode.py) over whichever workers arrived, giving the *exact*
full-batch gradient despite stragglers.

Worker layout (TPU-first): worker i holds its s+1 cyclic data chunks
device-resident (placed once at setup); the per-epoch payload is just the
weight vector — the minimal H2D transfer. The per-worker program is a
single fused jitted function: forward, gradient, and the coded linear
combination of its chunk gradients.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..backends.base import DelayFn
from ..backends.xla import XLADeviceBackend
from ..pool import AsyncPool, asyncmap, waitall
from ..ops.gradcode import GradientCode

__all__ = ["LogisticRegression", "CodedSGD"]


def _chunk_rows(N: int, n_workers: int) -> int:
    if N % n_workers != 0:
        raise ValueError(
            f"samples {N} must divide evenly into {n_workers} chunks"
        )
    return N // n_workers


class LogisticRegression:
    """Binary logistic regression with L2; pure-functional loss/grad."""

    def __init__(self, dim: int, l2: float = 1e-4):
        self.dim = dim
        self.l2 = l2

    def init(self) -> jnp.ndarray:
        return jnp.zeros(self.dim, dtype=jnp.float32)

    def loss(self, w, X, y):
        logits = X @ w
        # numerically stable BCE-with-logits
        nll = jnp.mean(
            jnp.maximum(logits, 0) - logits * y
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        )
        return nll + 0.5 * self.l2 * jnp.sum(w * w)

    def grad(self, w, X, y):
        return jax.grad(self.loss)(w, X, y)


@jax.jit
def _coded_grad(w, Xc, yc, coeffs):
    """Coded sum of per-chunk gradients on one worker.

    Xc: (s+1, rows, dim), yc: (s+1, rows), coeffs: (s+1,).
    Gradient of mean-BCE per chunk, combined with the code coefficients.
    Chunk gradients are computed in one vmapped pass — a single fused
    XLA program per epoch.
    """

    def chunk_grad(X, y):
        logits = X @ w
        p = jax.nn.sigmoid(logits)
        return X.T @ (p - y) / X.shape[0]

    grads = jax.vmap(chunk_grad)(Xc, yc)  # (s+1, dim)
    return coeffs @ grads


class CodedSGD:
    """Straggler-resilient SGD: one ``asyncmap`` per step, exact decode.

    >>> sgd = CodedSGD(X, y, n_workers=8, s=2)
    >>> w, history = sgd.fit(epochs=50, lr=0.5)
    """

    def __init__(
        self,
        X: np.ndarray,
        y: np.ndarray,
        n_workers: int,
        s: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        l2: float = 1e-4,
        seed: int = 0,
    ):
        N, dim = X.shape
        rows = _chunk_rows(N, n_workers)
        Xb = np.asarray(X, dtype=np.float32).reshape(n_workers, rows, dim)
        yb = np.asarray(y, dtype=np.float32).reshape(n_workers, rows)

        def chunk_data(sup, dev):
            return (
                jax.device_put(jnp.asarray(Xb[sup]), dev),
                jax.device_put(jnp.asarray(yb[sup]), dev),
            )

        self._setup(dim, n_workers, s, devices, delay_fn, l2, seed,
                    chunk_data)

    @classmethod
    def synthetic(
        cls,
        N: int,
        dim: int,
        n_workers: int,
        s: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        l2: float = 1e-4,
        seed: int = 0,
    ) -> "CodedSGD":
        """BASELINE-config-5 scale without host data: every worker's
        chunks are *generated on device* (jax.random, deterministic per
        chunk id), so a 1e6 x 1024 dataset never crosses the host<->device
        edge. Labels come from a hidden ``w*`` through a sigmoid, so the
        problem is learnable and convergence is measurable."""
        rows = _chunk_rows(N, n_workers)
        key = jax.random.key(seed)
        wkey, ckey = jax.random.split(key)
        wstar = jax.random.normal(wkey, (dim,), jnp.float32) * (dim ** -0.5)

        def gen_chunk(j):
            ck = jax.random.fold_in(ckey, j)
            X = jax.random.normal(ck, (rows, dim), jnp.float32)
            p = jax.nn.sigmoid(X @ wstar)
            y = jax.random.bernoulli(
                jax.random.fold_in(ck, 1), p
            ).astype(jnp.float32)
            return X, y

        gen_sup = jax.jit(jax.vmap(gen_chunk))

        def chunk_data(sup, dev):
            Xc, yc = gen_sup(jnp.asarray(sup))
            return jax.device_put(Xc, dev), jax.device_put(yc, dev)

        self = cls.__new__(cls)
        self._setup(dim, n_workers, s, devices, delay_fn, l2, seed,
                    chunk_data)
        return self

    def _setup(self, dim, n_workers, s, devices, delay_fn, l2, seed,
               chunk_data) -> None:
        """Shared construction: code, model, per-worker device chunk
        placement (via ``chunk_data(support, device)``), backend."""
        if devices is None:
            devices = jax.devices()
        self.n = n_workers
        self.s = s
        self.code = GradientCode(n_workers, s, seed=seed)
        self.model = LogisticRegression(dim, l2)
        self.l2 = l2
        self._chunks = []
        for i in range(n_workers):
            sup = self.code.support(i)
            dev = devices[i % len(devices)]
            Xc, yc = chunk_data(sup, dev)
            self._chunks.append((
                Xc, yc,
                jax.device_put(
                    jnp.asarray(self.code.B[i, sup], dtype=jnp.float32), dev
                ),
            ))
        self.backend = XLADeviceBackend(
            self._work, n_workers, devices=devices, delay_fn=delay_fn
        )

    def eval_data(self, worker: int = 0) -> tuple[jax.Array, jax.Array]:
        """The first data chunk held by ``worker``, as a device-resident
        ``(X, y)`` pair — for loss evaluation in examples/benchmarks
        without reaching into the internal chunk layout."""
        Xc, yc, _ = self._chunks[worker]
        return Xc[0], yc[0]

    def _work(self, i: int, payload: jax.Array, epoch: int) -> jax.Array:
        Xc, yc, coeffs = self._chunks[i]
        return _coded_grad(payload, Xc, yc, coeffs)

    def step(self, pool: AsyncPool, w, lr: float,
             epoch: int | None = None,
             nwait: int | None = None) -> jax.Array:
        """One coded-SGD step: asyncmap, decode + update *on device*.

        Accepts host or device ``w`` and returns the updated weights
        device-resident — feed them straight back in, so nothing but the
        tiny decode-weight solve touches the host between epochs (the
        coordinator's working state lives in HBM; per-worker gradient
        fetches would put n D2H transfers on the epoch critical path).
        ``nwait`` defaults to ``n - s`` (the code's tolerance); pass
        ``n`` to force a bulk-synchronous epoch (benchmark baselines).
        """
        if nwait is None:
            nwait = self.n - self.s
        dev = self.backend.devices[0]  # decode device (D2D on a slice)
        w = jax.device_put(jnp.asarray(w, dtype=jnp.float32), dev)
        asyncmap(pool, w, self.backend, nwait=nwait, epoch=epoch)
        fresh = pool.fresh_indices()
        a = jnp.asarray(self.code.decode_weights(fresh), jnp.float32)
        G = jnp.stack([
            jax.device_put(jnp.asarray(pool.results[i]), dev) for i in fresh
        ])
        # chunk gradients are per-chunk means; full-batch mean over n
        # chunks, plus the L2 term applied coordinator-side
        g = (a @ G) / self.n + self.l2 * w
        return w - lr * g

    def fit(self, epochs: int, lr: float = 0.5, w0: np.ndarray | None = None,
            X_eval: np.ndarray | None = None, y_eval: np.ndarray | None = None):
        """Run coded SGD; returns (w, history of per-epoch loss)."""
        if (X_eval is None) != (y_eval is None):
            raise ValueError("X_eval and y_eval must be provided together")
        pool = AsyncPool(self.n)
        w = np.zeros(self.model.dim, dtype=np.float32) if w0 is None else w0
        history = []
        eval_loss = jax.jit(self.model.loss)
        if X_eval is not None:  # device-resident once, not per epoch
            X_eval = jnp.asarray(X_eval)
            y_eval = jnp.asarray(y_eval)
        for e in range(1, epochs + 1):
            w = self.step(pool, w, lr)
            if X_eval is not None:
                history.append(float(eval_loss(w, X_eval, y_eval)))
        # drain in-flight stragglers so the shared backend is reusable
        # (a second fit() would otherwise find their slots occupied)
        waitall(pool, self.backend)
        return np.asarray(w), history
