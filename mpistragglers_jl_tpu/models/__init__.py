_HOME = {
    "LogisticRegression": "logreg",
    "CodedSGD": "logreg",
    "TransformerConfig": "transformer",
    "init_params": "transformer",
    "param_specs": "transformer",
    "forward_dense": "transformer",
    "make_forward": "transformer",
    "make_train_step": "transformer",
    "shard_params": "transformer",
}

__all__ = list(_HOME)


def __getattr__(name):
    # lazy: models pull in jax; keep the core package importable without it
    if name in _HOME:
        import importlib

        mod = importlib.import_module(f".{_HOME[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
