_HOME = {
    "LogisticRegression": "logreg",
    "CodedSGD": "logreg",
    "TransformerConfig": "transformer",
    "init_params": "transformer",
    "param_specs": "transformer",
    "forward_dense": "transformer",
    "make_forward": "transformer",
    "make_train_step": "transformer",
    "make_optax_train_step": "transformer",
    "optax_step": "transformer",
    "shard_params": "transformer",
    "batch_axes": "transformer",
    "data_spec": "transformer",
    "init_cache": "decode",
    "cache_specs": "decode",
    "decode_batch_axes": "decode",
    "shard_cache": "decode",
    "prefill_dense": "decode",
    "decode_step_dense": "decode",
    "decode_step_ring_dense": "decode",
    "generate_dense": "decode",
    "generate_ring_dense": "decode",
    "init_ring_cache": "decode",
    "make_ring_generate": "decode",
    "CodedGradTrainer": "coded_train",
    "transformer_chunk_loss": "coded_train",
    "generate_speculative_dense": "speculative",
    "make_speculative_dense": "speculative",
    "make_speculative": "speculative",
    "ring_from_cache": "decode",
    "Request": "serving",
    "ServingScheduler": "serving",
    "make_serving_scan": "serving",
    "serving_decode_step_dense": "serving",
    "PagePool": "paging",
    "PagePoolExhausted": "paging",
    "prefix_page_digests": "paging",
    "RequestRouter": "router",
    "RoutedRequest": "router",
    "ROUTER_POLICIES": "router",
    "PrefillWorker": "disagg",
    "DecodeReplica": "disagg",
    "MigrationPlanner": "disagg",
    "MigrationTicket": "disagg",
    "MigrationRing": "disagg",
    "MigrationRingReader": "disagg",
    "make_prefill": "decode",
    "make_decode_step": "decode",
    "make_extend": "decode",
    "make_generate": "decode",
    "init_moe_layer": "moe",
    "moe_layer_specs": "moe",
    "switch_route": "moe",
    "switch_route_indices": "moe",
    "moe_ffn_dense": "moe",
    "moe_ffn_sharded": "moe",
}

__all__ = list(_HOME) + ["clear_cached_programs"]


def clear_cached_programs() -> None:
    """Drop every lru-cached jitted program factory in the models
    package (dense generation runners, speculative runners, serving
    tick/admission programs). Compiled programs can pin device buffers;
    long-running hosts that sweep many shapes (benchmarks, services)
    call this between phases to release HBM. One public chokepoint so
    callers cannot silently miss a newly added cache."""
    from . import decode, serving, speculative

    for cache in (
        decode._dense_runner,
        speculative._spec_runner,
        serving._serving_scan_dense,
        serving._serving_scan_paged,
        serving._extend_chunk_dense,
        serving._finish_admit_dense,
        serving._place_dense,
        serving._seed_admit_paged,
        serving._place_paged,
        serving._copy_pages_paged,
        serving._gather_ring_paged,
    ):
        cache.cache_clear()


def __getattr__(name):
    # lazy: models pull in jax; keep the core package importable without it
    if name in _HOME:
        import importlib

        mod = importlib.import_module(f".{_HOME[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
