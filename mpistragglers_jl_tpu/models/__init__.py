__all__ = ["LogisticRegression", "CodedSGD"]


def __getattr__(name):
    # lazy: models pull in jax; keep the core package importable without it
    if name in ("LogisticRegression", "CodedSGD"):
        from . import logreg

        return getattr(logreg, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
