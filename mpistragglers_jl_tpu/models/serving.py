"""Continuous batching: a multi-request serving scheduler.

The reference is transport-only (src/MPIAsyncPools.jl:1-226 — no model,
no serving); this is north-star serving scope (VERDICT r4 next-#1),
converting the round-4 serving inventory (ring cache, GQA decode, int8
KV, speculative/hedged) from single-request features into aggregate
throughput. At B=1 a decode step is weight-read-bound — the HBM traffic
is the parameters, amortized over one token (docs/PERF.md). Batching S
concurrent requests into one step amortizes the same weight reads over
S tokens; until the KV-cache reads dominate, aggregate tokens/s scales
near-linearly with S. That economics is the whole point of this module.

Design (TPU-first):

* **Fixed slots, static shapes.** The scheduler owns ``S`` serving
  slots. Per-layer state is ONE batched O(W) ring cache
  ``(S, W, kv_heads, head_dim)`` — the ring layout
  (models/decode.py) makes every slot a fixed-size arena regardless of
  how long its request runs, so slot reuse is a row overwrite, never a
  reallocation, and one compiled program serves every scheduler tick.
* **Per-row positions.** Unlike ``decode_step_ring_dense`` (one scalar
  position for the whole batch), every slot decodes at its own global
  position: RoPE angles, ring-slot writes, and the ``kpos >= 0``
  validity mask are all computed per row (``_rope_rows``,
  ``_ring_write_rows``, ``_ring_attention_rows``). The masks make slot
  reuse safe: a freshly admitted row's unwritten slots have
  ``kpos < 0`` and self-mask, so the previous occupant's K/V are
  unreachable even before they are overwritten.
* **Inner scan, host ticks.** Each scheduler tick runs ``n_inner``
  decode steps for all S slots inside one ``lax.scan`` program — one
  host round trip per ``S x n_inner`` tokens (on the tunneled bench
  chip a round trip costs ~120 ms; per-token host control would bury
  the batching win).
* **Chunked prefill interleaved with decode.** Admission does not
  stall in-flight requests behind a long prompt: each tick advances
  every admitting request by ONE C-token prefill chunk (through the
  masked cached-attention path, exactly ``make_extend``'s semantics)
  and then runs the decode scan. With ``quantize_kv=True`` each chunk
  attends the already-quantized cache — the only math available once
  earlier chunks' raw K/V are gone — and per-position absmax
  quantization makes the chunk size invisible, so the stream is
  IDENTICAL at any ``prompt_chunk`` and equals the quantized oracle
  (``generate_ring_dense(quantize_kv=True)``, whose prefill runs the
  same cached-attention math — ADVICE r5 repaired in PR 1; both the
  identity and its chunk-invariance premise are pinned by
  tests/test_serving.py). A request's prefill lands in a
  transient positional cache; on the last chunk the final-W window
  gathers into its slot's ring rows (``ring_from_cache`` math with a
  traced length) and the first token comes from the last chunk's
  logits. Decode stall per tick is bounded by one chunk, not one
  prompt.
* **EOS retirement + slot reuse.** Rows that emit ``eos_id`` keep
  emitting it on-device (static shapes; ``_eos_clamp``); the host
  strips the tail, retires the request (EOS or its ``max_new`` budget),
  and hands the slot to the next queued request.

Greedy decoding per row equals the single-request oracle
(:func:`~.decode.generate_ring_dense`) token-for-token — the batched
per-row step is the same math evaluated at S independent (row,
position) points; tests/test_serving.py pins every admitted request
against its oracle stream, including staggered admissions and reuse.
One precision caveat: "same math" means same at exact f32 — at the
TPU's DEFAULT matmul precision (bf16 MXU passes) the batched and
single-request program shapes round differently and greedy argmax
TIES can flip between them (set
``jax.config.update("jax_default_matmul_precision", "highest")`` for
cross-shape exactness; examples/continuous_batching.py demonstrates).

``make_serving_scan(cfg, mesh=...)`` is the sharded variant of the
decode tick (slots over ``dp``, heads over ``tp``, the training path's
psum placement) — the multi-chip serving program the driver dryrun
compiles and checks against the dense tick.
"""

from __future__ import annotations

import functools
import time
from collections import deque
from typing import Any, Sequence

import numpy as np

import jax
from .. import _jax_compat  # noqa: F401  (installs older-JAX aliases)
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..obs.timeline import annotate as _annotate
from .decode import (
    _NEG,
    _cache_pv,
    _cache_scores,
    _check_ring_cfg,
    _check_sampling_params,
    _decode_kernel_enabled,
    _decode_kernel_interpreted,
    _UNSET,
    _eos_clamp,
    _incremental_forward,
    _is_quantized,
    _kernel_possible,
    _kernel_viable,
    _kv_quantize,
    _paged_kernel_possible,
    _pick_token,
    _ring_from_cache,
    _route_kernel,
)
from .paging import (
    NULL_PAGE,
    PagePool,
    PagePoolExhausted,
    prefix_page_digests,
)
from ..qos import DeficitScheduler, TenantRegistry
from .transformer import (
    TransformerConfig,
    _ln,
    _mlp,
    make_kv_slice,
    param_specs,
)

__all__ = [
    "Request",
    "ServingScheduler",
    "make_serving_scan",
    "serving_decode_step_dense",
    "PagePool",
    "PagePoolExhausted",
]


def _fresh_cache(cfg: TransformerConfig, B: int, L: int,
                 quantize_kv: bool = False) -> list[dict]:
    """Zeroed positional/ring cache with DISTINCT buffers per leaf.
    decode.py's ``_zero_cache_layer`` aliases one zeros array for k and
    v (fine undonated); the serving programs donate their caches, and
    donating the same buffer twice is an XLA execution error."""
    shape = (B, L, cfg.kv_heads, cfg.head_dim)
    kvdt = jnp.int8 if quantize_kv else cfg.dtype

    def layer():
        out = {"k": jnp.zeros(shape, kvdt), "v": jnp.zeros(shape, kvdt)}
        if quantize_kv:
            out["k_s"] = jnp.zeros(shape[:3], jnp.float32)
            out["v_s"] = jnp.zeros(shape[:3], jnp.float32)
        return out

    return [layer() for _ in range(cfg.n_layers)]


def _fresh_pages(cfg: TransformerConfig, n_pages: int, P: int,
                 quantize_kv: bool = False) -> list[dict]:
    """Zeroed per-layer PAGE POOL: K/V live in one flat
    ``(n_pages * P, kv_heads, head_dim)`` row arena per layer (scales
    ``(n_pages * P, kv_heads)`` when int8), shared by every slot —
    page ``p`` owns rows ``[p*P, (p+1)*P)``. Page 0 is the reserved
    null page (:data:`~.paging.NULL_PAGE`): rows nothing reads
    unmasked, the landing zone for retired-but-still-ticking rows."""
    shape = (n_pages * P, cfg.kv_heads, cfg.head_dim)
    kvdt = jnp.int8 if quantize_kv else cfg.dtype

    def layer():
        out = {"k": jnp.zeros(shape, kvdt), "v": jnp.zeros(shape, kvdt)}
        if quantize_kv:
            out["k_s"] = jnp.zeros(shape[:2], jnp.float32)
            out["v_s"] = jnp.zeros(shape[:2], jnp.float32)
        return out

    return [layer() for _ in range(cfg.n_layers)]


# --------------------------------------------------------------------------
# per-row primitives (each slot at its own global position)
# --------------------------------------------------------------------------


def _rope_rows(x, pos):
    """Rotary embedding for single-token rows: x (S, 1, H, D), pos (S,)
    global positions — the per-row counterpart of transformer._rope
    (which shares one position vector across the batch)."""
    Dh = x.shape[-1]
    half = Dh // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]  # (S, half)
    cos = jnp.cos(ang)[:, None, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, None, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )


def _ring_write_rows(cache_l: dict, k, v, slot):
    """Write each row's single-token K/V at its own ring slot:
    k, v (S, 1, Hkv, D), slot (S,) — a per-row scatter on the slot
    axis (decode.py's ``_cache_write`` writes one shared offset)."""
    rows = jnp.arange(k.shape[0])

    def put(c, u):
        return c.at[rows, slot].set(u[:, 0].astype(c.dtype))

    if not _is_quantized(cache_l):
        return {"k": put(cache_l["k"], k), "v": put(cache_l["v"], v)}
    kq, ks = _kv_quantize(k)
    vq, vs = _kv_quantize(v)
    return {
        "k": put(cache_l["k"], kq),
        "v": put(cache_l["v"], vq),
        "k_s": put(cache_l["k_s"], ks),
        "v_s": put(cache_l["v_s"], vs),
    }


def _ring_attention_rows(q, cache_l, pos, scale, use_kernel=False):
    """Single-query ring attention with a per-row position: the same
    ``kpos(s) = pos - ((pos - s) mod W), valid iff kpos >= 0`` invariant
    as decode.py's ``_ring_cached_attention``, evaluated rowwise. The
    mask is simultaneously causal bound, sliding-window bound, warmup
    guard, AND slot-reuse guard (a reused slot's stale rows sit at
    kpos < 0 for the new occupant until overwritten).

    ``use_kernel=True`` routes int8 caches through the Pallas decode
    kernel's ring mode (per-row positions ride SMEM): ONE kernel call
    serves all S slots, so the scan/custom_call boundary cost that
    sinks the kernel at B=1 is paid once per S tokens — the batched
    regime is where int8 finally converts its byte win into time
    (docs/PERF.md). Default False: this function is also the dense
    ORACLE step (``serving_decode_step_dense``), which stays einsum so
    kernel-vs-einsum parity is testable against it."""
    W = cache_l["k"].shape[1]
    if use_kernel and _kernel_viable(q, cache_l):
        from ..ops.decode_attention import quantized_decode_attention

        return quantized_decode_attention(
            q, cache_l, pos, scale, ring=True
        )
    s = _cache_scores(q, cache_l, scale)  # (S, H, 1, W) f32
    kpos = pos[:, None] - jnp.mod(
        pos[:, None] - jnp.arange(W)[None, :], W
    )  # (S, W)
    s = jnp.where((kpos >= 0)[:, None, None, :], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    o = _cache_pv(p, cache_l)
    return o.astype(q.dtype)


def _paged_write_rows(cache_l: dict, k, v, pt, slot, P: int):
    """Write each row's single-token K/V through its page table:
    ring slot ``slot[i]`` of row i lives at pool row
    ``pt[i, slot // P] * P + slot % P``. The scheduler's pre-tick COW
    pass guarantees every page written here is exclusively owned (or
    the null page, for retired rows) — the device program never has to
    know pages can be shared."""
    rows = jnp.arange(k.shape[0])
    phys = pt[rows, slot // P] * P + slot % P  # (S,)

    def put(c, u):
        return c.at[phys].set(u[:, 0].astype(c.dtype))

    if not _is_quantized(cache_l):
        return {"k": put(cache_l["k"], k), "v": put(cache_l["v"], v)}
    kq, ks = _kv_quantize(k)
    vq, vs = _kv_quantize(v)
    return {
        "k": put(cache_l["k"], kq),
        "v": put(cache_l["v"], vq),
        "k_s": put(cache_l["k_s"], ks),
        "v_s": put(cache_l["v_s"], vs),
    }


def _paged_gather(cache_l: dict, pt, W: int, P: int):
    """Materialize every slot's W-row ring view out of the page pool:
    one PAGE-BLOCK ``jnp.take`` per leaf — ``(S, max_pages)`` indices
    moving contiguous P-row blocks. Page p's rows are ring slots
    ``[j*P, (j+1)*P)`` in offset order, so reshaping the block gather
    yields EXACTLY the slot-ring layout ``(S, W, ...)`` and the einsum
    path runs the unchanged dense ring math on it — dense and paged
    decode are the identical math by construction, which is what the
    CPU parity tests lean on. Speed note: this gather runs once per
    TICK (hoisted out of the decode scan — see ``_serving_scan_paged``;
    a per-step gather measured 0.66x the slot tick). Null page-table
    entries resolve to page 0, whose rows are only ever reached by
    ``kpos < 0`` (masked) slots."""
    S = pt.shape[0]
    flat = pt.reshape(-1)  # (S * max_pages,)
    return {
        kk: jnp.take(
            a.reshape((a.shape[0] // P, P) + a.shape[1:]), flat, axis=0
        ).reshape((S, W) + a.shape[1:])
        for kk, a in cache_l.items()
    }


def _paged_scatter(cache_l: dict, view_l: dict, pt, P: int):
    """Write a tick's updated ring views back through the page table —
    the inverse of :func:`_paged_gather`, one page-block scatter per
    leaf. Duplicate table entries (a prefix page shared by several
    slots) all write the SAME bytes: any page a tick writes is
    exclusively owned (the pre-tick COW pass), so shared pages come
    back exactly as they went out. Null entries dump into page 0,
    which nothing reads unmasked."""
    flat = pt.reshape(-1)
    out = {}
    for kk, a in cache_l.items():
        paged_shape = (a.shape[0] // P, P) + a.shape[1:]
        upd = view_l[kk].astype(a.dtype).reshape(
            (flat.shape[0],) + paged_shape[1:]
        )
        out[kk] = a.reshape(paged_shape).at[flat].set(upd).reshape(
            a.shape
        )
    return out


def _paged_attention_rows(q, cache_l, pt, pos, scale, P):
    """Single-query ring attention THROUGH the page table — the Pallas
    paged KERNEL route only (ops/decode_attention.py): the per-slot
    page-index row rides scalar-prefetch SMEM next to the per-row
    positions and the block index maps gather K/V pages directly, so
    HBM traffic is the W live rows. The einsum tick never reads
    through the table per step — ``_serving_scan_paged`` hoists the
    gather out of the scan instead (``_paged_gather`` + the unchanged
    dense ring math). Routing is resolved at scheduler construction
    (``_paged_kernel_possible``); there is no trace-time re-gate."""
    from ..ops.decode_attention import quantized_decode_attention

    return quantized_decode_attention(
        q, cache_l, pos, scale, ring=True, page_table=pt,
        page_tokens=P,
    )


def _serving_layer(x, lp, cache_l, pos, cfg, *, kv_slice=None,
                   tp_psum=False, use_kernel=False, paged=None):
    """One layer of the per-row serving step (the dense-FFN half of
    decode.py's ``_incremental_layer`` with per-row positions).
    ``paged`` = (page_table, W, PAGE_TOKENS) switches the cache
    write/read to the page-pool layout; None is the slot-ring path."""
    h = _ln(x, lp["ln1_s"], lp["ln1_b"])
    q = jnp.einsum("bld,dhk->blhk", h, lp["wq"])
    k = jnp.einsum("bld,dhk->blhk", h, lp["wk"])
    v = jnp.einsum("bld,dhk->blhk", h, lp["wv"])
    if kv_slice is not None:
        k, v = kv_slice(k), kv_slice(v)
    q, k = _rope_rows(q, pos), _rope_rows(k, pos)
    scale = cfg.head_dim ** -0.5
    if paged is not None:
        # kernel route only: the einsum paged tick runs THIS function
        # with paged=None over per-tick gathered ring views instead
        # (see _serving_scan_paged)
        pt, W, P = paged
        cache_l = _paged_write_rows(cache_l, k, v, pt, jnp.mod(pos, W), P)
        o = _paged_attention_rows(q, cache_l, pt, pos, scale, P)
    else:
        W = cache_l["k"].shape[1]
        cache_l = _ring_write_rows(cache_l, k, v, jnp.mod(pos, W))
        o = _ring_attention_rows(q, cache_l, pos, scale,
                                 use_kernel=use_kernel)
    attn_out = jnp.einsum("blhk,hkd->bld", o, lp["wo"])
    if tp_psum:
        attn_out = jax.lax.psum(attn_out, "tp")
    x = x + attn_out
    h2 = _ln(x, lp["ln2_s"], lp["ln2_b"])
    y = _mlp(h2, lp)
    if tp_psum:
        y = jax.lax.psum(y, "tp")
    return x + y + lp["b2"], cache_l


def _serving_forward(params, tok, pos, caches, cfg, *, kv_slice=None,
                     tp_psum=False, use_kernel=False, paged=None):
    """(tok (S,), pos (S,), caches) -> (logits (S, V), caches)."""
    x = params["emb"][tok[:, None]]  # (S, 1, d)
    new = []
    for lp, cl in zip(params["layers"], caches):
        x, cl = _serving_layer(x, lp, cl, pos, cfg, kv_slice=kv_slice,
                               tp_psum=tp_psum, use_kernel=use_kernel,
                               paged=paged)
        new.append(cl)
    x = _ln(x, params["lnf_s"], params["lnf_b"])
    logits = jnp.einsum("bld,vd->blv", x, params["emb"])
    return logits[:, 0], new


def serving_decode_step_dense(params, tok, pos, caches,
                              cfg: TransformerConfig):
    """One batched serving decode step, dense: every slot at its own
    position. Returns (logits (S, V), caches). The single-position
    sibling is :func:`~.decode.decode_step_ring_dense`. Always the
    einsum path — this is the reference step the kernelized tick is
    pinned against."""
    _check_ring_cfg(cfg)
    return _serving_forward(params, tok, pos, caches, cfg)


def _pick_rows(lg, pos, keys, temperature, top_k, dtype):
    """Per-row token choice: greedy at temperature 0 (static), else
    per-row keyed sampling — each row evaluated as row 0 of its own
    B=1 stream THROUGH ``decode._pick_token`` itself (vmapped), so the
    fold/truncation discipline has one source of truth and a slot's
    sampled stream equals ``generate_ring_dense(..., key=key_row)``
    for the same request key by construction."""
    if temperature == 0.0:
        return jnp.argmax(lg, axis=-1).astype(dtype)
    return jax.vmap(
        lambda k, p, ll: _pick_token(
            ll[None], p, k, temperature, top_k, dtype
        )[0]
    )(keys, pos, lg)


def _scan_body(params, tok, pos, done, caches, cfg, eos_id, n_inner,
               keys, *, temperature=0.0, top_k=None,
               kv_slice=None, tp_psum=False, use_kernel=False,
               paged=None):
    """``n_inner`` decode steps for all S slots under one scan (greedy,
    or per-row keyed sampling when ``temperature > 0``; ``keys`` is
    required — a silent shared-default key would couple every
    scheduler's streams).
    Returns (tok, pos, done, caches, toks (S, n_inner))."""

    def step(carry, _):
        tok, pos, done, caches = carry
        lg, caches = _serving_forward(
            params, tok, pos, caches, cfg, kv_slice=kv_slice,
            tp_psum=tp_psum, use_kernel=use_kernel, paged=paged,
        )
        nxt = _pick_rows(lg, pos, keys, temperature, top_k, tok.dtype)
        nxt, done = _eos_clamp(nxt, tok, done, eos_id)
        return (nxt, pos + 1, done, caches), nxt

    (tok, pos, done, caches), toks = jax.lax.scan(
        step, (tok, pos, done, caches), None, length=n_inner
    )
    return tok, pos, done, caches, toks.swapaxes(0, 1)


@functools.lru_cache(maxsize=32)
def _serving_scan_dense(cfg: TransformerConfig, n_inner: int,
                        eos_id: int | None, temperature: float = 0.0,
                        top_k: int | None = None,
                        use_kernel: bool = False):
    """Jitted dense tick: (params, tok, pos, done, caches, keys) ->
    (tok, pos, done, caches, toks). Caches donated — the tick updates
    the arena in place in HBM. ``use_kernel`` is the scheduler's
    RESOLVED int8-kernel routing (part of the cache key, so toggling
    the global routes on the next scheduler construction)."""

    @functools.partial(jax.jit, donate_argnums=(4,))
    def run(params, tok, pos, done, caches, keys):
        return _scan_body(params, tok, pos, done, caches, cfg, eos_id,
                          n_inner, keys, temperature=temperature,
                          top_k=top_k, use_kernel=use_kernel)

    return run


@functools.lru_cache(maxsize=32)
def _serving_scan_paged(cfg: TransformerConfig, n_inner: int,
                        eos_id: int | None, temperature: float,
                        top_k: int | None, use_kernel: bool, P: int):
    """Jitted PAGED tick: like :func:`_serving_scan_dense` plus the
    ``(S, max_pages)`` int32 page table (a loop-invariant input — the
    tick writes pages, never the table; COW retargeting happens
    host-side between ticks). The page pool is donated like the ring
    arena; ``W = max_pages * P`` is recovered from the table shape so
    one compiled program serves any pool size at a given (cfg, P).

    ``use_kernel=True`` (the int8 route) reads pages IN PLACE every
    step — the Pallas page-table mode's whole point. The einsum
    fallback instead hoists the indirection OUT of the scan: the table
    is tick-invariant, so each layer's W-row ring view gathers ONCE,
    the unchanged dense ring scan runs on the views (the paged einsum
    tick IS the slot-ring tick on a gathered arena — parity by
    construction), and one scatter writes the views back through the
    table. A per-step gather measured 0.66x the slot tick on the bench
    box (XLA re-materializes the view every step inside the scan);
    hoisted, the gather amortizes over ``n_inner`` steps and the tick
    lands within the <= 5% budget. The trade is a transient
    ``(S, W)``-row view per layer during the tick — active-slot bytes,
    not pool bytes; the kernel route has no such transient (docs/
    PERF.md byte model)."""

    @functools.partial(jax.jit, donate_argnums=(4,))
    def run(params, tok, pos, done, caches, keys, pt):
        W = pt.shape[1] * P
        if use_kernel:
            return _scan_body(
                params, tok, pos, done, caches, cfg, eos_id, n_inner,
                keys, temperature=temperature, top_k=top_k,
                use_kernel=True, paged=(pt, W, P),
            )
        views = [_paged_gather(cl, pt, W, P) for cl in caches]
        tok, pos, done, views, toks = _scan_body(
            params, tok, pos, done, views, cfg, eos_id, n_inner, keys,
            temperature=temperature, top_k=top_k, use_kernel=False,
        )
        caches = [
            _paged_scatter(cl, vw, pt, P)
            for cl, vw in zip(caches, views)
        ]
        return tok, pos, done, caches, toks

    return run


@functools.lru_cache(maxsize=32)
def _seed_admit_paged(cfg: TransformerConfig, R: int, P: int):
    """Seed rows ``[0, ell)`` of a transient positional prefill cache
    from shared prefix pages: ring slot s of a within-window prefix
    holds position s, so the page rows ARE the positional rows and
    admission can skip recomputing them. ``R`` (static) bounds the
    gather at ``min(W, Lmax)``; rows at and past ``ell`` stay zero —
    exactly the arena :func:`_fresh_cache` hands to prefill. The
    seeded bytes are the pages' bytes, which are the bytes this very
    prefill would have produced (pinned by the paged parity tests), so
    the oracle identity survives the skip. Cache donated; the page
    pool is only read."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(cache, pages, pt_row, ell):
        s = jnp.arange(R)
        phys = pt_row[s // P] * P + s % P
        valid = s < ell

        def seed(c, pg):
            g = jnp.take(pg, phys, axis=0)  # (R, ...)
            g = jnp.where(
                valid.reshape((R,) + (1,) * (g.ndim - 1)), g, 0
            )
            return jax.lax.dynamic_update_slice_in_dim(
                c, g[None].astype(c.dtype), 0, axis=1
            )

        return [
            {kk: seed(cl[kk], pl[kk]) for kk in cl}
            for cl, pl in zip(cache, pages)
        ]

    return run


@functools.lru_cache(maxsize=32)
def _gather_ring_paged(cfg: TransformerConfig, P: int):
    """Materialize ONE slot's full W-row ring view out of the page
    pool — the capture half of a KV-page migration (models/disagg.py):
    the gathered leaves are fresh device buffers, so the source
    scheduler can free (and reuse) the slot's pages the moment this
    returns while the view stays valid for the destination's
    :func:`_place_paged` scatter. Shapes match ``_finish_admit_dense``'s
    ring output exactly — adoption IS a re-placement. The pool is only
    read (no donation)."""

    @jax.jit
    def run(caches, pt_row):
        W = pt_row.shape[0] * P
        return [_paged_gather(cl, pt_row[None], W, P) for cl in caches]

    return run


@functools.lru_cache(maxsize=32)
def _place_paged(cfg: TransformerConfig, P: int):
    """Paged install: scatter the admitted request's W ring rows into
    its pages and set the row state — :func:`_place_dense` with the
    cache row write routed through the page table. Shared prefix rows
    write bytes IDENTICAL to what the pages already hold (the seed op
    put those very bytes into the transient cache), so the
    unconditional scatter never perturbs a sharer; rows past the
    request's page budget land in the null page."""

    @functools.partial(jax.jit, donate_argnums=(0, 2, 3, 4))
    def run(caches, ring, tok, pos, done, keys, pt_row, s, tok0, pos0,
            key):
        W = ring[0]["k"].shape[1]
        srows = jnp.arange(W)
        phys = pt_row[srows // P] * P + srows % P
        caches = [
            {kk: c[kk].at[phys].set(r[kk][0].astype(c[kk].dtype))
             for kk in c}
            for c, r in zip(caches, ring)
        ]
        return (caches, tok.at[s].set(tok0), pos.at[s].set(pos0),
                done.at[s].set(False), keys.at[s].set(key))

    return run


@functools.lru_cache(maxsize=32)
def _copy_pages_paged(cfg: TransformerConfig, P: int):
    """BATCHED COW page copies across every layer and leaf: all of a
    tick's ``src -> dst`` pairs in ONE jitted call (one dispatch on
    the tick's critical path however many sharers diverge at once,
    review r11), donated so the pool updates in place. Every src block
    is gathered BEFORE any dst block writes, so a page appearing as
    src twice (three-way sharing, two writers in one tick) reads its
    pre-copy bytes both times; dst pages are freshly allocated and
    never coincide with a src. The scheduler pads the pair lists to a
    power-of-two length with null-page self-copies (page 0 -> page 0,
    bytes nothing reads unmasked) to bound compile count."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(caches, src, dst):
        def cp(a):
            paged = a.reshape((a.shape[0] // P, P) + a.shape[1:])
            blk = jnp.take(paged, src, axis=0)
            return paged.at[dst].set(blk).reshape(a.shape)

        return [{kk: cp(cl[kk]) for kk in cl} for cl in caches]

    return run


def make_serving_scan(cfg: TransformerConfig, mesh: Mesh, n_inner: int,
                      *, eos_id: int | None = None,
                      quantize_kv: bool = False,
                      temperature: float = 0.0,
                      top_k: int | None = None):
    """Sharded serving tick: slots over ``dp``, heads over ``tp``
    (psum placement of the training path — the serving counterpart of
    :func:`~.decode.make_decode_step` with per-row positions).
    Returns ``f(params, tok, pos, done, caches, keys)`` jitted over
    ``mesh`` with the caches donated (``keys``: per-slot PRNG keys,
    used only at ``temperature > 0``). ``quantize_kv=True`` serves an int8 ring
    cache (scale leaves shard like their K/V; the per-row write/score
    paths detect the layout)."""
    _check_ring_cfg(cfg)
    _check_sampling_params(temperature, top_k)
    if cfg.n_experts:
        raise ValueError(
            "serving scheduler covers dense-FFN configs; MoE decode "
            "routes per chunk (models/decode.py prefill caveat) and is "
            "served via make_generate"
        )
    tp = int(mesh.shape["tp"])
    if cfg.kv_heads % tp != 0 and tp % cfg.kv_heads != 0:
        raise ValueError(
            f"kv_heads {cfg.kv_heads} and tp {tp} must nest (one "
            "divide the other) for the sharded serving tick's cache "
            "layout"
        )
    # kv_heads < tp uses decode.py's replicated-groups layout: the
    # cache's global head axis has `tp` slots, slot t holding kv head
    # t*kv_heads//tp (each device computes its slot locally from the
    # tp-replicated K/V projections via make_kv_slice — no extra
    # collectives). Callers size the cache head axis with
    # `_cache_heads_global(cfg, mesh)` exactly like make_ring_generate.
    cspec = P("dp", None, "tp", None)
    layer_spec = {"k": cspec, "v": cspec}
    if quantize_kv:
        sspec = P("dp", None, "tp")
        layer_spec["k_s"], layer_spec["v_s"] = sspec, sspec
    cspecs = [dict(layer_spec) for _ in range(cfg.n_layers)]
    # make-time snapshot of the int8-kernel toggle (decode.py's
    # discipline: routing and check_vma must come from one reading)
    use_kernel = _decode_kernel_enabled()

    def local(params, tok, pos, done, caches, keys):
        # resolve at this shard's slot count: one ring-kernel call per
        # layer serves every local slot, so the auto gate compares the
        # per-call boundary cost against S_local amortizing rows
        routed = (
            _kernel_possible(cfg, quantize_kv, use_kernel)
            and _route_kernel(use_kernel, tok.shape[0])
        )
        return _scan_body(
            params, tok, pos, done, caches, cfg, eos_id, n_inner,
            keys, temperature=temperature, top_k=top_k,
            kv_slice=make_kv_slice(cfg), tp_psum=True,
            use_kernel=routed,
        )

    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs(cfg, mesh), P("dp"), P("dp"), P("dp"),
                  cspecs, P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp"), cspecs,
                   P("dp", None)),
        # quantize_kv + the kernel toggle routes the int8 ring kernel
        # inside the tick — interpreted Pallas needs the same vma
        # carve-out as decode.py's make_decode_step; einsum-only
        # programs keep varying-axes checking on
        check_vma=not _decode_kernel_interpreted(cfg, quantize_kv,
                                                 use_kernel),
    )
    return jax.jit(f, donate_argnums=(4,))


# --------------------------------------------------------------------------
# admission programs (chunked prefill -> ring window -> slot)
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _extend_chunk_dense(cfg: TransformerConfig, C: int, Lmax: int):
    """One C-token prefill chunk into a (1, Lmax) transient positional
    cache at dynamic ``offset`` (make_extend semantics, dense B=1).
    Cache donated: chunks stream through one arena."""

    @functools.partial(jax.jit, donate_argnums=(2,))
    def run(params, chunk, cache, offset):
        logits, cache = _incremental_forward(
            params, chunk, cache, offset, cfg, prefill=False
        )
        return logits, cache

    return run


@functools.lru_cache(maxsize=32)
def _finish_admit_dense(cfg: TransformerConfig, Lmax: int,
                        temperature: float = 0.0,
                        top_k: int | None = None):
    """Gather the last-W window of a filled positional cache into ring
    rows + pick the first token (greedy, or sampled with the request's
    key at the prompt's last position — decode.py's fold discipline):
    (cache, last_logits (1, C, V), true_len, last_off, key) ->
    (tok0 (), ring leaves (1, W, ...))."""
    W = _check_ring_cfg(cfg)

    @jax.jit
    def run(cache, last_logits, true_len, last_off, key):
        ring = [_ring_from_cache(cl, true_len, W) for cl in cache]
        lg = jnp.take(last_logits[0], true_len - 1 - last_off, axis=0)
        tok0 = _pick_rows(
            lg[None], (true_len - 1)[None], key[None], temperature,
            top_k, jnp.int32,
        )[0]
        return tok0, ring

    return run


@functools.lru_cache(maxsize=32)
def _place_dense(cfg: TransformerConfig):
    """Install an admitted request into slot ``s``: ring rows into the
    batched cache, first token + start position into the row state.
    Everything donated — admission is an in-place row write."""

    @functools.partial(jax.jit, donate_argnums=(0, 2, 3, 4))
    def run(caches, ring, tok, pos, done, keys, s, tok0, pos0, key):
        caches = [
            {kk: c[kk].at[s].set(r[kk][0].astype(c[kk].dtype))
             for kk in c}
            for c, r in zip(caches, ring)
        ]
        return (caches, tok.at[s].set(tok0), pos.at[s].set(pos0),
                done.at[s].set(False), keys.at[s].set(key))

    return run


# --------------------------------------------------------------------------
# observability (obs/ registry + timeline, strictly opt-in)
# --------------------------------------------------------------------------


class _ServingObs:
    """Instrument bundle for one scheduler, resolved ONCE at
    construction so the tick path only increments/observes. Built only
    when a registry or span recorder is attached — a dark scheduler's
    tick does no observability work beyond ``is not None`` checks (the
    tracer's opt-in contract, utils/trace.py), which the no-op
    overhead test in tests/test_obs.py pins.
    """

    def __init__(self, sched: "ServingScheduler", registry, spans):
        self.registry = registry
        self.spans = spans
        self.annotate = _annotate
        # tokens delivered in the CURRENT tick (admission first-tokens
        # + trimmed decode harvest — the same population as
        # serving_tokens_total, so the per-tick rate and the running
        # counter always cross-check)
        self._tick_toks = 0
        # last published page-pool tallies (delta counters)
        self._last_share = 0
        self._last_cow = 0
        self._r = registry is not None
        if not self._r:
            return
        registry.gauge(
            "serving_slots", help="configured serving slots"
        ).set(sched.S)
        self.m_queue = registry.gauge(
            "serving_queue_depth",
            help="requests queued, not yet admitted",
        )
        self.m_active = registry.gauge(
            "serving_active_slots", help="slots decoding or admitting"
        )
        self.m_ticks = registry.counter("serving_ticks_total")
        self.m_tick_s = registry.histogram(
            "serving_tick_seconds", help="scheduler tick wall clock"
        )
        self.m_tokens = registry.counter(
            "serving_tokens_total",
            help="tokens delivered into request streams (first tokens "
            "+ decode harvest, post-retirement trim)",
        )
        self.m_tok_rate = registry.gauge(
            "serving_tokens_per_s",
            help="tokens delivered / tick wall, last tick",
        )
        self.m_ttft = registry.histogram(
            "serving_ttft_seconds", help="submit -> first token"
        )
        self.m_intertoken = registry.histogram(
            "serving_intertoken_seconds",
            help="mean per-token gap, one sample per (slot, tick)",
        )
        self.m_admitted = registry.counter("serving_admitted_total")
        self.m_retired = {
            "eos": registry.counter(
                "serving_retired_total", reason="eos"
            ),
            "length": registry.counter(
                "serving_retired_total", reason="length"
            ),
        }
        self.m_prefill = registry.counter(
            "serving_prefill_chunks_total",
            help="admission prefill chunks advanced",
        )
        # the AUTO gate's resolved decision for THIS scheduler (fixed
        # at construction against its slot count — see use_kernel);
        # incremented once per decode tick, so the series records when
        # the kernel route actually fired, not just that it could
        self.m_route = registry.counter(
            "serving_kernel_route_total",
            help="decode ticks by resolved int8-kernel route",
            route="kernel" if sched.use_kernel else "einsum",
        )
        # page-pool series (paged schedulers only): pool occupancy
        # gauges plus prefix-share / COW counters published as deltas
        # of the pool's lifetime tallies, so the registry stays
        # monotone however often the pool is sampled
        if sched.paged:
            self.m_pages_free = registry.gauge(
                "serving_cache_pages_free",
                help="KV cache pages on the free list",
            )
            self.m_pages_used = registry.gauge(
                "serving_cache_pages_used",
                help="KV cache pages allocated to slots",
            )
            # tier-labeled (cache/ package): hbm = local share, the
            # only tier a fleet-less scheduler ever increments;
            # dram/peer appear lazily via fleet_hit when a fleet
            # cache serves the page instead
            self.m_share = registry.counter(
                "serving_prefix_share_hits_total",
                help="prompt prefix pages whose prefill was skipped "
                "at admission, by serving tier (hbm = local share, "
                "dram = host page store, peer = replica fetch)",
                tier="hbm",
            )
            self._share_tier: dict[str, Any] = {"hbm": self.m_share}
            self.m_cow = registry.counter(
                "serving_cow_copies_total",
                help="copy-on-write page copies (a slot wrote a page "
                "another slot still reads)",
            )
        # QoS series (qos= schedulers only): per-tenant admission
        # counters plus deficit / page-quota-usage gauges, series
        # created lazily per tenant and cached (the _RouterObs
        # per-labelset pattern — label churn is bounded by the
        # registry's tenant count)
        self._qos = getattr(sched, "_qos", None)
        if self._qos is not None:
            self._q_admit: dict[str, Any] = {}
            self._q_deficit: dict[str, Any] = {}
            self._q_quota: dict[str, Any] = {}

    # -- hooks (each guards its own registry half) ----------------------
    def qos_admitted(self, sched: "ServingScheduler",
                     tenant: str) -> None:
        if not self._r or self._qos is None:
            return
        c = self._q_admit.get(tenant)
        if c is None:
            cls = (self._qos.get(tenant).cls
                   if tenant in self._qos else "unknown")
            c = self._q_admit[tenant] = self.registry.counter(
                "qos_admitted_total",
                help="requests admitted into slots, by tenant and "
                "SLO class (DRR order)",
                tenant=tenant, cls=cls,
            )
        c.inc()

    def qos_gauges(self, sched: "ServingScheduler") -> None:
        """Per-tenant deficit + quota-usage gauges, refreshed once per
        tick (tick_done)."""
        drr = sched._drr
        for contract in self._qos:
            t = contract.name
            g = self._q_deficit.get(t)
            if g is None:
                g = self._q_deficit[t] = self.registry.gauge(
                    "qos_deficit",
                    help="carried DRR credit (tokens) per tenant",
                    tenant=t,
                )
            g.set(drr.deficit(t))
            if sched.paged:
                q = self._q_quota.get(t)
                if q is None:
                    q = self._q_quota[t] = self.registry.gauge(
                        "qos_pages_quota_used",
                        help="KV pages attributed to the tenant "
                        "(hot refs + cold cache) against its quota",
                        tenant=t,
                    )
                q.set(sched._tenant_usage(t))

    def first_token(self, req: "Request", t: float) -> None:
        self._tick_toks += 1
        if self._r:
            self.m_admitted.inc()
            self.m_tokens.inc()
            if req._t_submit is not None:
                self.m_ttft.observe(t - req._t_submit)
        req._t_last_tok = t

    def tokens_emitted(self, req: "Request", n: int, t: float) -> None:
        self._tick_toks += n
        if self._r:
            self.m_tokens.inc(n)
            last = req._t_last_tok
            if last is not None and n:
                self.m_intertoken.observe((t - last) / n)
        req._t_last_tok = t

    def prefill_chunk(self) -> None:
        if self._r:
            self.m_prefill.inc()

    def fleet_hit(self, tier: str) -> None:
        """One prefix page served from the fleet cache (``dram`` |
        ``peer``) instead of prefilled — the same family as the local
        share counter, so tier shares read off one query."""
        if not self._r:
            return
        c = self._share_tier.get(tier)
        if c is None:
            c = self._share_tier[tier] = self.registry.counter(
                "serving_prefix_share_hits_total", tier=tier,
            )
        c.inc()

    def tick_done(
        self, sched: "ServingScheduler", retired, t0: float,
        t1: float, t2: float | None,
    ) -> None:
        """t0 tick begin, t1 admissions done, t2 decode scan fetched
        (None when no slot decoded this tick)."""
        t3 = time.perf_counter()
        wall = t3 - t0
        n_toks, self._tick_toks = self._tick_toks, 0
        if self._r:
            self.m_ticks.inc()
            self.m_tick_s.observe(wall)
            self.m_queue.set(sched.pending)
            self.m_active.set(sched.active)
            self.m_tok_rate.set(n_toks / wall if wall > 0 else 0.0)
            if t2 is not None:
                self.m_route.inc()
            for req in retired:
                self.m_retired[req.reason].inc()
            if sched.paged:
                pool = sched.pool
                self.m_pages_free.set(pool.free)
                self.m_pages_used.set(pool.used)
                self.m_share.inc(pool.share_hits - self._last_share)
                self._last_share = pool.share_hits
                self.m_cow.inc(pool.cow_copies - self._last_cow)
                self._last_cow = pool.cow_copies
            if self._qos is not None:
                self.qos_gauges(sched)
        sp = self.spans
        if sp is not None:
            tick = sched.tick_count
            sp.add(
                f"tick {tick}", t0, wall, track="scheduler",
                queue=sched.pending, active=sched.active,
                tokens=n_toks, retired=len(retired),
            )
            sp.add("admit", t0, t1 - t0, track="scheduler")
            if t2 is not None:
                sp.add("decode", t1, t2 - t1, track="scheduler")
                sp.add("retire", t2, t3 - t2, track="scheduler")
            sp.count("queue_depth", sched.pending, t=t3)
            sp.count("active_slots", sched.active, t=t3)
            if sched.paged:
                sp.count("pages_used", sched.pool.used, t=t3)


# --------------------------------------------------------------------------
# the scheduler
# --------------------------------------------------------------------------


class Request:
    """One generation request: ``prompt`` (1D int tokens) in,
    ``tokens`` (the generated ids, EOS kept if emitted) out.
    ``finished`` flips at retirement; ``reason`` is ``"eos"``,
    ``"length"``, or ``"cancelled"`` (withdrawn via
    :meth:`ServingScheduler.cancel` — the router's losing hedge leg).
    ``tenant`` names the contract the request is billed to (the QoS
    plane, ``qos/``); None = untenanted (the default on schedulers
    without ``qos=``)."""

    _next_id = 0

    def __init__(self, prompt, max_new: int, key=None,
                 tenant: str | None = None):
        self.id = Request._next_id
        Request._next_id += 1
        # per-request PRNG key (sampling schedulers); None -> id-derived
        self.key = key
        self.tenant = tenant
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        self.max_new = int(max_new)
        self.tokens: list[int] = []
        self.finished = False
        self.reason: str | None = None
        # filled by the scheduler: admission tick and retirement tick,
        # the observability hooks the tests and bench read
        self.admitted_tick: int | None = None
        self.retired_tick: int | None = None
        # latency stamps (perf_counter), set only by an instrumented
        # scheduler (registry=/spans=): submit time and last-token time
        self._t_submit: float | None = None
        self._t_last_tok: float | None = None
        # incremental EOS-scan state (scheduler-internal): index of the
        # first EOS if found, and how many tokens were already scanned
        self._eos_at: int | None = None
        self._scanned = 0
        # causal tracing (round 22): the TraceBook id following this
        # request across planes (None = dark). _trace_owned marks a
        # trace MINTED at this scheduler's door — terminal events are
        # stamped by the owner only (a router-managed leg's terminals
        # belong to the router, obs/tracing.py docstring)
        self.trace: int | None = None
        self._trace_owned = False


class _Admitting:
    """Per-slot chunked-prefill state machine: the transient positional
    cache plus the chunk cursor. Paged admissions additionally carry
    the page plan: ``base`` (tokens of shared prefix whose prefill is
    SKIPPED — chunk i runs at offset ``base + i*C``), ``pids`` (the
    slot's full page table, installed into the device table only at
    finish — until then the row's stale writes land in the null page),
    ``digests``/``n_cover`` (prefix digests to register at finish) and
    ``wraps`` (whether this request can wrap its ring — registered
    pages are then volatile)."""

    def __init__(self, req: Request, cache, padded, n_chunks: int, *,
                 base: int = 0, pids=None, digests=(), n_cover: int = 0,
                 wraps: bool = False):
        self.req = req
        self.cache = cache
        self.padded = padded  # (1, n_chunks * C) int32
        self.n_chunks = n_chunks
        self.next_chunk = 0
        self.last_logits = None
        self.base = base
        self.pids = pids
        self.digests = digests
        self.n_cover = n_cover
        self.wraps = wraps


class ServingScheduler:
    """Continuous-batching scheduler over ``slots`` fixed serving
    slots (dense single-device programs; the sharded tick is
    :func:`make_serving_scan`).

    >>> sched = ServingScheduler(params, cfg, slots=8, eos_id=2)
    >>> r = sched.submit(prompt, max_new=64)   # any time, any order
    >>> sched.run()                            # or step() per tick
    >>> r.tokens                               # greedy == oracle

    Each ``step()`` tick: (1) advance every admitting request by one
    prefill chunk, installing finished ones into their slot; (2) admit
    queued requests into free slots; (3) run ``n_inner`` decode steps
    for all slots in one device program; (4) harvest tokens, retire
    rows that emitted EOS or exhausted their budget, free their slots.
    Greedy by default; ``temperature > 0`` (optionally ``top_k``)
    samples each slot with its request's own key (``submit(...,
    key=...)``; id-derived when omitted) — a sampled stream equals
    ``generate_ring_dense(..., key=request_key)`` exactly, like the
    greedy==oracle contract.

    ``prompt_chunk`` bounds the decode stall a long prompt can inject
    into in-flight requests (one chunk per tick); ``max_prompt`` sizes
    the transient prefill arena (one compile for all prompt lengths).

    ``page_tokens=P`` switches the cache from per-slot rings to the
    PAGED pool (docs/API.md "Paged serving cache"): per-layer K/V live
    in ``cache_pages`` fixed-size pages of P ring slots managed by a
    host-side :class:`PagePool` (free list + refcounts), each slot
    reading through a ``(max_pages,)`` page-index row. Three wins over
    the slot ring, same token streams (the oracle identity holds
    verbatim — the paged parity tests pin it):

    * **Right-sized residency.** A request holds only the pages its
      lifetime can touch (``ceil(min(W, Tp + max_new + n_inner) / P)``)
      instead of a full ``W``-slot arena — short requests stop
      stranding HBM, and ``cache_pages`` (not ``slots``) becomes the
      capacity knob. Admission defers when the pool cannot cover a
      request's whole budget, so mid-decode exhaustion cannot happen.
      The DEFERRAL UNIT is the admission-order contract: FIFO (the
      default) defers the head of the one queue — no reordering, a
      large request cannot be starved by later small ones; under
      ``qos=`` the deficit-round-robin hook defers only that TENANT's
      queue while the rotation tries the next, so one tenant's
      unplannable head never blocks another tenant's admission.

    **Multi-tenant QoS** (``qos=`` a :class:`~..qos.TenantRegistry`,
    docs/API.md "Multi-tenant QoS"): ``submit`` then requires
    ``tenant=`` (unknown tenants refused by name) and admission order
    comes from a :class:`~..qos.DeficitScheduler` over per-tenant
    queues — weighted, work-conserving, deficits carried — instead of
    FIFO. Paged schedulers additionally enforce each contract's page
    QUOTA at plan time, with COW-aware graceful reclaim: a retiring
    request's still-registered, refcount-1 prefix pages go COLD
    (resident for future sharers, attributed to the tenant) instead
    of freeing, and reclaim evicts cold pages oldest-first — an
    over-quota tenant's first — while a page shared with any live
    holder (refcount > 1) is never touched.
    * **Prefix sharing.** Admission hashes the prompt's page-aligned
      prefix (chained digests — page j's key covers ``prompt[:(j+1) *
      P]``, the exact content determinant) and shares resident pages
      by refcount, SKIPPING their prefill entirely: N users on one
      system prompt pay its prefill and residency once while any
      sharer is resident.
    * **Copy-on-write.** Writers never touch a shared page: the
      pre-tick pass copies any page the next ``n_inner`` steps would
      write while its refcount > 1 (reserved at admission for
      window-wrapping requests), so a reader's bytes are immutable.

    The decode tick reads K/V through the page table: the einsum path
    gathers each slot's W-row ring view (``jnp.take`` — identical math
    to the slot ring, the CPU-testable fallback); int8 caches route
    the Pallas kernel's page-table mode, where the per-slot page row
    rides scalar-prefetch SMEM and block index maps gather pages
    directly (no materialized ring view at all).

    Observability is strictly opt-in (the tracer contract): pass
    ``registry=`` (an :class:`~..obs.MetricsRegistry`) for tick/queue/
    slot/tokens-per-s series, TTFT and inter-token histograms, and
    kernel-route counters, and/or ``spans=`` (an
    :class:`~..obs.SpanRecorder`) for per-tick admit/decode/retire
    spans in the merged Perfetto timeline
    (:func:`~..obs.dump_merged_chrome_trace`); ``flight=`` (an
    :class:`~..obs.FlightRecorder`) for per-tick spans in the bounded
    postmortem ring plus the ``last_tick_at`` liveness stamp a flight
    watchdog probes; ``exporter=`` (an :class:`~..obs.ObsServer`) to
    register the tick-freshness ``/healthz`` check and the span
    recorder as a ``/trace`` source. With none of them, the tick path
    does no observability work at all.
    """

    def __init__(self, params, cfg: TransformerConfig, *, slots: int = 8,
                 n_inner: int = 8, eos_id: int | None = None,
                 prompt_chunk: int = 256, max_prompt: int = 2048,
                 quantize_kv: bool = False, temperature: float = 0.0,
                 top_k: int | None = None, page_tokens: int | None = None,
                 cache_pages: int | None = None,
                 qos: TenantRegistry | None = None,
                 max_queue: int | None = None, registry=None,
                 spans=None, flight=None, exporter=None, trace=None,
                 cache=None):
        W = _check_ring_cfg(cfg)
        _check_sampling_params(temperature, top_k)
        if cfg.n_experts:
            raise ValueError(
                "serving scheduler covers dense-FFN configs (MoE: see "
                "make_serving_scan's error note)"
            )
        if slots < 1 or n_inner < 1:
            raise ValueError("slots and n_inner must be >= 1")
        if prompt_chunk > max_prompt:
            raise ValueError("prompt_chunk must be <= max_prompt")
        self.paged = page_tokens is not None
        if self.paged:
            self.P = int(page_tokens)
            if self.P < 1 or W % self.P != 0:
                raise ValueError(
                    f"page_tokens must divide the attention window "
                    f"(W={W}), got {page_tokens}"
                )
            self.max_pages = W // self.P
        elif cache_pages is not None:
            raise ValueError(
                "cache_pages without page_tokens: pass page_tokens to "
                "enable the paged cache"
            )
        self.params = params
        self.cfg = cfg
        self.S = int(slots)
        self.W = W
        self.n_inner = int(n_inner)
        self.eos_id = eos_id
        self.C = int(prompt_chunk)
        self.Lmax = int(max_prompt)
        self.quantize_kv = bool(quantize_kv)
        # queue-depth ceiling (chaos plane): the scheduler's own
        # bounded-queue backstop. The ROUTER is the shed point (it
        # refuses by name with a reason before a replica ever sees the
        # request); this ceiling is the hard assertion behind it — a
        # submit past it is a caller bug surfaced by name, never an
        # unbounded deque.
        if max_queue is not None and max_queue < 1:
            raise ValueError(
                f"max_queue must be >= 1 or None, got {max_queue}"
            )
        self.max_queue = None if max_queue is None else int(max_queue)
        self._queue: deque[Request] = deque()
        # multi-tenant QoS (opt-in): admission order moves from the
        # FIFO deque to a weighted deficit-round-robin scheduler over
        # per-tenant queues, and paged admission enforces page quotas
        # with cold-page reclaim (class docstring; qos/ package)
        self._qos = qos
        self._drr = DeficitScheduler(qos) if qos is not None else None
        if qos is not None and len(qos) == 0:
            raise ValueError(
                "qos= needs at least one TenantContract registered: "
                "an empty registry can admit nothing"
            )
        if qos is not None:
            # per-tenant page accounting: hot refs (pages the tenant's
            # resident slots hold) + cold pages (retired prefix pages
            # kept resident, attributed to the tenant that landed
            # them); quota usage is their sum
            self._tenant_pages: dict[str, int] = {}
            self._cold: dict[int, str] = {}  # pid -> tenant, oldest first
            self._cold_count: dict[str, int] = {}
        self._slot_req: list[Request | None] = [None] * self.S
        self._admitting: dict[int, _Admitting] = {}  # slot -> state
        self.tick_count = 0
        # device-resident row state + batched ring cache arena
        self.temperature = float(temperature)
        self.top_k = top_k
        self._tok = jnp.zeros((self.S,), jnp.int32)
        self._pos = jnp.zeros((self.S,), jnp.int32)
        self._done = jnp.ones((self.S,), bool)  # idle rows stay done
        self._keys = jax.random.split(jax.random.key(0), self.S)
        if self.paged:
            # page-pool arena: the capacity knob is cache_pages, not
            # slots x W. The default matches the slot-ring footprint
            # (every slot could hold a full window) plus the null page
            # — opting into paging never means LESS capacity.
            n_pages = (
                int(cache_pages) if cache_pages is not None
                else self.S * self.max_pages + 1
            )
            if n_pages < self.max_pages + 1:
                raise ValueError(
                    f"cache_pages {n_pages} cannot hold even one "
                    f"window-filling request ({self.max_pages} pages "
                    "+ the null page)"
                )
            self.pool = PagePool(n_pages, self.P)
            self._caches = _fresh_pages(cfg, n_pages, self.P,
                                        self.quantize_kv)
            # host-authoritative page table; the device copy refreshes
            # lazily whenever admission/COW/retirement dirties it
            self._pt_host = np.full((self.S, self.max_pages),
                                    NULL_PAGE, np.int32)
            self._pt_dev = None
            # per-slot global position mirror (the COW pass must know
            # which ring pages the NEXT tick will write, host-side)
            self._host_pos = [0] * self.S
            # per-slot wrap flag: whether the resident request's
            # lifetime can wrap the ring — its departure must drop the
            # wrapper count on every page it holds (paging.py)
            self._slot_wraps = [False] * self.S
        else:
            self.pool = None
            self._caches = _fresh_cache(cfg, self.S, W, self.quantize_kv)
        # int8 Pallas kernel routing, resolved at construction against
        # THIS scheduler's slot count (decode.py's auto gate: the tick
        # batches all S slots into one kernel call per layer, which is
        # what amortizes the scan boundary cost the B=1 path cannot).
        # The paged tick adds the page-geometry conditions
        # (_paged_kernel_possible) — all cfg-static, so the resolution
        # stays a construction-time decision either way.
        if self.paged:
            self.use_kernel = (
                _paged_kernel_possible(cfg, self.quantize_kv, self.P)
                and _route_kernel(_UNSET, self.S)
            )
            self._scan = _serving_scan_paged(
                cfg, self.n_inner, eos_id, self.temperature, top_k,
                self.use_kernel, self.P,
            )
            self._seed = _seed_admit_paged(cfg, min(W, self.Lmax),
                                           self.P)
            self._place_p = _place_paged(cfg, self.P)
            self._copy = _copy_pages_paged(cfg, self.P)
            self._gather = _gather_ring_paged(cfg, self.P)
        else:
            self.use_kernel = (
                _kernel_possible(cfg, self.quantize_kv)
                and _route_kernel(_UNSET, self.S)
            )
            self._scan = _serving_scan_dense(
                cfg, self.n_inner, eos_id, self.temperature, top_k,
                self.use_kernel,
            )
        self._extend = _extend_chunk_dense(cfg, self.C, self.Lmax)
        self._finish = _finish_admit_dense(
            cfg, self.Lmax, self.temperature, top_k
        )
        self._place = _place_dense(cfg)
        # instruments resolved once here; None = dark (no tick cost)
        self._obs = (
            _ServingObs(self, registry, spans)
            if registry is not None or spans is not None
            else None
        )
        # flight recorder (obs/flight.py, opt-in): per-tick spans land
        # in the bounded postmortem ring; dark schedulers never stamp
        self._flight = flight
        # perf_counter of the latest completed tick — the liveness
        # signal for /healthz tick-freshness checks and flight
        # watchdogs; stays None on a fully dark scheduler (the dark
        # tick reads no clocks, pinned by tests/test_obs.py). An
        # exporter-ONLY scheduler must stamp too — its registered
        # health check reads this, and a never-set stamp would report
        # an actively-ticking scheduler as stuck forever.
        self.last_tick_at: float | None = None
        self._stamp_ticks = (
            self._obs is not None or flight is not None
            or exporter is not None
        )
        # causal tracing (round 22, opt-in per GC004): request
        # lifecycle events on the wall clock; dark schedulers pay one
        # `is None` check per transition
        self._trace = None
        if trace is not None:
            self.attach_trace(trace)
        # fleet prefix cache (cache/ package, opt-in): admission
        # probes the fleet namespace for page-aligned prefixes it
        # cannot share locally, fetching from host DRAM or a peer
        # replica instead of prefilling; reclaimed cold pages spill
        # the other way. Requires the paged arena — the fleet unit is
        # the page.
        self.cache = cache
        self.cache_name: str | None = None
        if cache is not None:
            if not self.paged:
                raise ValueError(
                    "cache= needs the paged arena: pass page_tokens "
                    "(the fleet cache's unit is the prefix page)"
                )
            self.cache_name = cache.attach(self)
        if exporter is not None:
            # register the tick-freshness health check (+ the span
            # recorder as a /trace source) on the ObsServer
            exporter.register_scheduler(self)

    def attach_trace(self, book) -> None:
        """Arm causal tracing (constructor ``trace=`` routes here; a
        router propagates its book the same way). DRR admission
        transitions ride the scheduler's trace hook — qos/ itself
        stays clock-free."""
        self._trace = book
        if self._drr is not None:
            self._drr.set_trace(self._drr_trace_event)

    def _drr_trace_event(self, kind, tenant, item, cost) -> None:
        tid = item.trace
        if tid is not None:
            self._trace.event(
                tid, kind, time.perf_counter(), tenant=tenant,
                cost=cost,
            )

    # -- public API -----------------------------------------------------

    def enable_tick_stamping(self) -> None:
        """Turn on the per-tick ``last_tick_at`` liveness stamp (one
        ``perf_counter`` read per tick). Construction with any of
        ``registry=``/``spans=``/``flight=``/``exporter=`` enables it
        already; :meth:`ObsServer.register_scheduler` calls this so a
        scheduler registered AFTER dark construction becomes probeable
        — its tick-freshness health check reads the stamp."""
        self._stamp_ticks = True

    def submit(self, prompt, max_new: int, key=None,
               tenant: str | None = None, trace=None) -> Request:
        """Queue a request; returns the live :class:`Request` whose
        ``tokens``/``finished`` the caller watches. Admission happens
        inside subsequent ticks — requests may arrive while others are
        mid-decode (the "straggling request" case). ``key``: the
        request's PRNG key when the scheduler samples
        (``temperature > 0``); defaults to a request-id-derived key.
        A sampled stream equals ``generate_ring_dense(..., key=key)``
        for the same key (tests pin it). ``tenant``: the contract the
        request is billed to — REQUIRED on a ``qos=`` scheduler
        (unknown tenants refused by name); on a plain scheduler the
        tag merely rides the request."""
        if key is not None and self.temperature == 0.0:
            raise ValueError(
                "submit(key=...) on a greedy scheduler: the key would "
                "be silently unused — construct the scheduler with "
                "temperature > 0 (generate_* raises the same way)"
            )
        if self.max_queue is not None and self.pending >= self.max_queue:
            raise RuntimeError(
                f"queue ceiling: {self.pending} requests already "
                f"queued at max_queue={self.max_queue} — shed at the "
                "router (shed_depth=) instead of queueing unboundedly"
            )
        if self._qos is not None:
            if tenant is None:
                raise ValueError(
                    "qos scheduler needs tenant= at submit: admission "
                    "order and page quotas are per-contract (register "
                    "a catch-all TenantContract for untagged traffic)"
                )
            self._qos.get(tenant)  # unknown tenant: named KeyError
        req = Request(prompt, max_new, key=key, tenant=tenant)
        if req.prompt.size > self.Lmax:
            raise ValueError(
                f"prompt of {req.prompt.size} tokens exceeds max_prompt "
                f"{self.Lmax}; raise max_prompt (one-time recompile)"
            )
        obs = self._obs
        if obs is not None:
            req._t_submit = time.perf_counter()
        if trace is not None:
            # router-minted id: the leg joins an existing record
            req.trace = trace
        elif self._trace is not None:
            # this scheduler IS the entry door: mint here and own the
            # terminal events
            req.trace = self._trace.mint()
            req._trace_owned = True
            self._trace.event(
                req.trace, "submitted", time.perf_counter(),
                tenant=tenant, prompt=int(req.prompt.size),
            )
        if self._drr is not None:
            # DRR cost is in tokens (prompt + budget — the same unit
            # as the contracts' rate budgets), so fairness is fair
            # chip work, not fair request counts
            self._drr.enqueue(
                tenant, req, float(req.prompt.size + req.max_new)
            )
        else:
            self._queue.append(req)
        if obs is not None and obs._r:
            obs.m_queue.set(self.pending)
        return req

    @property
    def active(self) -> int:
        """Slots currently decoding or admitting."""
        return sum(r is not None for r in self._slot_req)

    @property
    def pending(self) -> int:
        return (self._drr.total if self._drr is not None
                else len(self._queue))

    def _decode_scan_fetch(self) -> np.ndarray:
        """Run the jitted decode tick and fence the tokens to host."""
        if self.paged:
            (self._tok, self._pos, self._done, self._caches,
             toks) = self._scan(self.params, self._tok, self._pos,
                                self._done, self._caches, self._keys,
                                self._device_pt())
        else:
            (self._tok, self._pos, self._done, self._caches,
             toks) = self._scan(self.params, self._tok, self._pos,
                                self._done, self._caches, self._keys)
        return np.asarray(toks)  # (S, n_inner) one fetch per tick

    def _device_pt(self):
        """The device page table, refreshed from the host-authoritative
        copy when admission/COW/retirement dirtied it."""
        if self._pt_dev is None:
            self._pt_dev = jnp.asarray(self._pt_host)
        return self._pt_dev

    def step(self) -> list[Request]:
        """One scheduler tick; returns the requests retired in it
        (including any that retire at admission — max_new == 1 or a
        first-token EOS). When instrumented (``registry=``/``spans=``)
        the tick records admit/decode/retire spans and the queue/slot/
        token series; dark, the only additions to the hot path are
        ``obs is not None`` checks."""
        obs = self._obs
        flight = self._flight
        lit = self._stamp_ticks  # obs, flight, OR exporter attached
        t0 = time.perf_counter() if lit else 0.0
        self.tick_count += 1
        retired: list[Request] = []
        self._advance_admissions(retired)
        self._admit_from_queue(retired)
        t1 = time.perf_counter() if obs is not None else 0.0
        t2 = None
        decoding = [
            s for s, r in enumerate(self._slot_req)
            if r is not None and s not in self._admitting
        ]
        if decoding:
            if self.paged:
                # COW pass: every page the next n_inner writes touch
                # must be exclusively owned BEFORE the jitted scan runs
                # (the device program never sees shared pages)
                self._prepare_tick_pages(decoding)
            if obs is None:
                host = self._decode_scan_fetch()
            else:
                # device-side span: visible inside jax.profiler traces
                # on real chips, a no-op wherever the profiler is not
                with obs.annotate("serving.decode_scan"):
                    host = self._decode_scan_fetch()
                t2 = time.perf_counter()
            if self.paged:
                for s in decoding:
                    self._host_pos[s] += self.n_inner
            for s in decoding:
                req = self._slot_req[s]
                n_before = len(req.tokens) if obs is not None else 0
                req.tokens.extend(int(t) for t in host[s])
                due = self._retire_if_due(req)
                if obs is not None:
                    # count AFTER the retirement trim: the EOS-clamped
                    # tail the host strips was never delivered to
                    # anyone, and a tokens/s series inflated by it
                    # would overstate throughput by up to n_inner-1
                    # per retiring request
                    obs.tokens_emitted(
                        req, len(req.tokens) - n_before, t2
                    )
                if due:
                    self._free_slot(s)
                    retired.append(req)
        if obs is not None:
            obs.tick_done(self, retired, t0, t1, t2)
        if lit:
            now = time.perf_counter()
            self.last_tick_at = now
            if flight is not None:
                flight.span(
                    f"tick {self.tick_count}", t0, now - t0,
                    src="scheduler", track="scheduler",
                    queue=self.pending, active=self.active,
                    retired=len(retired),
                )
                flight.counter(
                    "serving_ticks_total", self.tick_count, t=now
                )
        return retired

    def cancel(self, req: Request) -> bool:
        """Withdraw ``req`` wherever it currently is — queued, mid-
        admission, or decoding — freeing its slot (and, paged, its
        pages) for the next request. Returns True when the request was
        live here and is now retired with ``reason == "cancelled"``;
        False when it already finished or was never this scheduler's
        (both leave it untouched). The replica hook the request ROUTER
        leans on: a hedged request's losing leg must stop consuming
        slot-ticks the moment the other replica's first token wins
        (models/router.py, first-token-wins)."""
        if req.finished:
            return False
        if self._drr is not None:
            removed = self._drr.remove(req)
        else:
            try:
                self._queue.remove(req)
                removed = True
            except ValueError:
                removed = False
        if removed:
            self._retire_cancelled(req)
            return True
        for s, r in enumerate(self._slot_req):
            if r is req:
                st = self._admitting.pop(s, None)
                if st is not None and self.paged:
                    # mid-admission the slot's pages live in the plan
                    # (_pt_host[s] stays NULL until finish), so
                    # _free_slot's table walk would miss them — release
                    # the committed plan here
                    n_refs = 0
                    for pid in st.pids:
                        if pid != NULL_PAGE:
                            self.pool.decref(int(pid), wrapper=st.wraps)
                            n_refs += 1
                    self._tenant_debit(req.tenant, n_refs)
                self._free_slot(s)
                self._retire_cancelled(req)
                return True
        return False

    def _retire_cancelled(self, req: Request) -> None:
        req.finished = True
        req.reason = "cancelled"
        req.retired_tick = self.tick_count
        # terminal events belong to the request's OWNER: only traces
        # minted at THIS door get their cancel stamped here (a router
        # leg's cancel is the router's reap, not the request's end)
        if self._trace is not None and req.trace is not None \
                and req._trace_owned:
            self._trace.event(
                req.trace, "cancelled", time.perf_counter(),
                tick=self.tick_count,
            )

    # -- KV-page migration (models/disagg.py's replica hooks) -----------
    #
    # The disaggregation subsystem moves a DECODING request between
    # paged schedulers: export gathers the slot's ring view out of the
    # page pool (fresh device buffers) plus the row's sampler/position
    # state and frees the slot; adopt re-plans pages in the destination
    # pool (sharing resident prefix digests exactly like admission,
    # reservations included), scatters the view back through the new
    # table, and re-registers the prefix-digest chain so COW sharing
    # survives the move. Between the two calls the request is resident
    # NOWHERE — the planner (MigrationPlanner) owns that window,
    # including its cancellation contract.

    def _migration_slot(self, req: Request) -> int | None:
        """The slot of a migratable request: resident, past admission
        (first token emitted), not finished. None otherwise."""
        if not self.paged or req.finished or not req.tokens:
            return None
        for s, r in enumerate(self._slot_req):
            if r is req and s not in self._admitting:
                return s
        return None

    def _page_row_bytes(self) -> int:
        """Bytes one page carries across every layer and leaf."""
        total = 0
        for cl in self._caches:
            for a in cl.values():
                total += a.nbytes * self.P // a.shape[0]
        return total

    def _page_payload(self, pid: int) -> np.ndarray:
        """One page's KV bytes as a flat uint8 array: per layer (list
        order), per leaf (SORTED key order — the frame-serialization
        convention of disagg.py), the page's row slice. This layout IS
        the fleet cache's wire/storage format: two schedulers with the
        same config produce byte-identical payloads for the same
        digest, which is what the spill/fetch parity tests pin."""
        P = self.P
        parts = []
        for cl in self._caches:
            for kk in sorted(cl):
                a = np.asarray(cl[kk][pid * P:(pid + 1) * P])
                parts.append(
                    np.ascontiguousarray(a).reshape(-1).view(np.uint8)
                )
        return np.concatenate(parts)

    def _install_page_payload(self, pid: int, payload) -> None:
        """Scatter a :meth:`_page_payload`-format byte string into
        page ``pid`` of this arena (the fetch landing). The split
        walks the same layer/sorted-leaf order; a size mismatch is a
        geometry bug refused by name (the cache hub validates
        page-byte equality at attach, so this only fires on config
        drift between attach and fetch)."""
        P = self.P
        buf = np.asarray(payload).reshape(-1).view(np.uint8)
        if buf.size != self._page_row_bytes():
            raise ValueError(
                f"page payload is {buf.size} bytes, this arena's "
                f"pages are {self._page_row_bytes()}"
            )
        off = 0
        for cl in self._caches:
            for kk in sorted(cl):
                a = cl[kk]
                row_shape = (P,) + a.shape[1:]
                nb = a.dtype.itemsize * int(np.prod(row_shape))
                vals = np.frombuffer(
                    buf[off:off + nb].tobytes(), dtype=a.dtype
                ).reshape(row_shape)
                cl[kk] = a.at[pid * P:(pid + 1) * P].set(
                    jnp.asarray(vals)
                )
                off += nb

    def _spill_page(self, pid: int, *,
                    tenant: str | None = None) -> None:
        """Offer a still-registered, sole-held page to the fleet
        cache's DRAM tier before it is freed/evicted. Reads the bytes
        BEFORE the freeing decref — a registered page's content still
        matches its digest (note_write/COW drop registration first).
        No-ops when the fleet already holds the digest somewhere else
        (re-spilling wastes the eviction bandwidth)."""
        d = self.pool.digest_of(pid)
        if d is None:
            return
        if not self.cache.wants(d, exclude=self.cache_name):
            return
        self.cache.spill(d, self._page_payload(pid), tenant=tenant)

    def migration_nbytes(self, req: Request) -> int:
        """Payload bytes a migration of ``req`` would move —
        ``pages_held * page_bytes`` summed over layers and leaves (the
        PERF round-16 byte model). 0 when ``req`` is not migratable
        here (queued, mid-admission, finished, or not this
        scheduler's)."""
        s = self._migration_slot(req)
        if s is None:
            return 0
        n_pages = int(np.sum(self._pt_host[s] != NULL_PAGE))
        return n_pages * self._page_row_bytes()

    def export_page_state(self, req: Request) -> dict:
        """Capture ``req``'s decode state as a portable page-layout
        image and FREE its slot (pages decref'd — shared prefixes just
        drop a reference). The returned dict is everything
        :meth:`adopt_page_state` needs to continue the stream
        token-for-token on another scheduler with the same params and
        generation config: the gathered ``(1, W, ...)`` ring view per
        layer (fresh device buffers — independent of this pool's
        later reuse), the row's token/position/PRNG-key state, and the
        prefix-digest chain for re-registration. The request object
        itself is NOT finished or mutated — it is simply resident
        nowhere until adopted."""
        s = self._migration_slot(req)
        if s is None:
            raise ValueError(
                "export_page_state: request must be decoding on this "
                "paged scheduler (queued/mid-admission/finished "
                "requests have no page image to move)"
            )
        pos = self._host_pos[s]
        n_pages = int(np.sum(self._pt_host[s] != NULL_PAGE))
        # prefix pages still hold the content their digests describe
        # only while no ring write has wrapped past W (decode writes
        # land at positions >= Tp; position p >= W overwrites page
        # (p mod W) // P). The chain is a pure function of the prompt
        # (paging.py), so it is recomputed rather than carried.
        clean = pos <= self.W and req.prompt.size <= self.W
        if clean:
            digests = prefix_page_digests(req.prompt, self.P,
                                          self.max_pages)
            n_cover = min(req.prompt.size // self.P, self.max_pages)
        else:
            digests, n_cover = [], 0
        ring = self._gather(
            self._caches, jnp.asarray(self._pt_host[s], jnp.int32)
        )
        state = {
            "request": req,
            "prompt": req.prompt,
            "tokens": list(req.tokens),
            "max_new": req.max_new,
            "tok": int(np.asarray(self._tok)[s]),
            "pos": int(pos),
            "key_data": np.asarray(jax.random.key_data(self._keys[s])),
            "ring": ring,
            "digests": tuple(digests),
            "n_cover": int(n_cover),
            "n_pages": n_pages,
            "P": self.P,
            "W": self.W,
            "quantize_kv": self.quantize_kv,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "eos_id": self.eos_id,
        }
        self._free_slot(s)
        return state

    def _check_adopt_compat(self, state: dict) -> None:
        for k, want in (
            ("P", self.P), ("W", self.W),
            ("quantize_kv", self.quantize_kv),
            ("temperature", self.temperature), ("top_k", self.top_k),
            ("eos_id", self.eos_id),
        ):
            if state[k] != want:
                raise ValueError(
                    f"adopt_page_state: {k} mismatch (source "
                    f"{state[k]!r}, this scheduler {want!r}) — tiers "
                    "must share page geometry and generation config "
                    "for the stream to continue token-for-token"
                )

    def _plan_adopt(self, state: dict, *, reclaim: bool = False):
        """(slot, shared pids, n_pages, wraps, reserve) for adopting
        ``state``, or None when no free slot / pool capacity covers
        it — the same whole-lifetime budget as admission planning, so
        PagePoolExhausted stays unreachable mid-decode. On a qos
        scheduler, cold pages count as reclaimable headroom (cache,
        not entitlement — the two-tier liveness contract: a stream is
        resident NOWHERE while its migration waits): ``reclaim=True``
        (the adopt path) actually evicts the shortfall; False (the
        ``can_adopt_state`` predicate) only counts it, so a
        feasibility probe never drains a replica's cold prefix cache
        as a side effect."""
        free_s = next(
            (s for s, r in enumerate(self._slot_req)
             if r is None and s not in self._admitting), None,
        )
        if free_s is None:
            return None
        Tp = int(state["prompt"].size)
        horizon = Tp + state["max_new"] + self.n_inner
        wraps = horizon > self.W
        n_pages = -(-min(self.W, horizon) // self.P)
        shared: list[int] = []
        for d in state["digests"][: min(state["n_cover"], n_pages)]:
            pid = self.pool.lookup(d)
            if pid is None:
                break
            shared.append(pid)
        reserve = sum(
            1 for pid in shared
            if self.pool.share_needs_reserve(pid, wraps)
        )
        shortfall = (n_pages - len(shared) + reserve
                     + self.pool.reserved - self.pool.free)
        if shortfall > 0:
            if self._drr is None:
                return None
            sset = set(shared)
            if reclaim:
                for _ in range(shortfall):
                    if not self._evict_cold_page(protect=sset):
                        return None
            else:
                evictable = sum(
                    1 for pid in self._cold if pid not in sset
                )
                if evictable < shortfall:
                    return None
        return free_s, shared, n_pages, wraps, reserve

    def can_adopt_state(self, state: dict) -> bool:
        """Would :meth:`adopt_page_state` succeed right now? (A free
        slot plus pool capacity for the request's whole-lifetime page
        budget, shared resident prefixes counted.) Boolean under ALL
        refusals — a config-mismatched state is False, not a raise, so
        the router's adoption gate can scan a heterogeneous tier
        without crashing the step loop."""
        if not self.paged:
            return False
        try:
            self._check_adopt_compat(state)
        except ValueError:
            return False
        return self._plan_adopt(state) is not None

    def could_adopt_state(self, state: dict) -> bool:
        """Would :meth:`adopt_page_state` EVER succeed here — i.e. does
        the whole-lifetime page budget fit this scheduler's pool even
        when every slot and page is free? False means parking a
        migration on this replica's capacity can never resolve (the
        pool is statically too small or the config mismatches); the
        two-tier router bounces such tickets back to the prefill tier
        instead of stranding the captured stream."""
        if not self.paged:
            return False
        try:
            self._check_adopt_compat(state)
        except ValueError:
            return False
        Tp = int(state["prompt"].size)
        horizon = Tp + state["max_new"] + self.n_inner
        n_pages = -(-min(self.W, horizon) // self.P)
        # an empty pool has n_pages-1 usable pages (page 0 is the null
        # page); prefix sharing could only lower the demand
        return n_pages <= self.pool.n_pages - 1

    def adopt_page_state(self, state: dict,
                         request: Request | None = None) -> Request:
        """Land a migrated request (:meth:`export_page_state` on the
        source) in this scheduler: allocate its page budget (sharing
        resident prefix-digest pages with COW reservations exactly
        like admission), scatter the carried ring view through the new
        page table, install the row's token/position/key state, and
        re-register the prefix-digest chain so future admissions and
        migrations keep sharing. Shared pages are scattered with bytes
        identical to what they already hold (same params, same prefix
        — the ``_place_paged`` admission argument), so sharers are
        never perturbed. ``request``: override the continued request
        object (cross-process adoption rebuilds one; in-process the
        captured object rides in ``state`` and keeps streaming)."""
        if not self.paged:
            raise ValueError(
                "adopt_page_state on an unpaged scheduler: migration "
                "is a page-layout transfer (construct with "
                "page_tokens=)"
            )
        self._check_adopt_compat(state)
        plan = self._plan_adopt(state, reclaim=True)
        if plan is None:
            raise PagePoolExhausted(
                "adopt_page_state: no free slot or page capacity for "
                "the migrated request (gate on can_adopt_state)"
            )
        s, shared, n_pages, wraps, _ = plan
        req = request if request is not None else state["request"]
        if req is None:
            req = Request(state["prompt"], state["max_new"])
            req.tokens = list(state["tokens"])
            req._scanned = len(req.tokens)
        pids = [NULL_PAGE] * self.max_pages
        for j, pid in enumerate(shared):
            self.pool.share(
                pid, reserve=self.pool.share_needs_reserve(pid, wraps),
                wrapper=wraps,
            )
            pids[j] = pid
            if self._trace is not None and req is not None \
                    and req.trace is not None:
                self._trace.event(
                    req.trace, "share_hit", time.perf_counter(),
                    page=int(pid),
                )
            if self._drr is not None and pid in self._cold:
                self._warm_cold(pid)
        try:
            for j in range(len(shared), n_pages):
                pids[j] = self.pool.alloc()
        except PagePoolExhausted:
            # roll back: a planned adoption must never half-commit
            for pid in pids:
                if pid != NULL_PAGE:
                    self.pool.decref(int(pid), wrapper=wraps)
            raise
        if self._drr is not None and req is not None \
                and getattr(req, "tenant", None) is not None:
            # migrated streams carry their tenant: the destination's
            # quota ledger takes the pages over (enforcement stays an
            # admission-time decision — an in-flight stream is never
            # evicted mid-decode)
            self._tenant_pages[req.tenant] = (
                self._tenant_pages.get(req.tenant, 0) + n_pages
            )
        self._pt_host[s] = pids
        self._pt_dev = None
        self._host_pos[s] = state["pos"]
        self._slot_wraps[s] = wraps
        rkey = jax.random.wrap_key_data(jnp.asarray(state["key_data"]))
        ring = [
            {kk: jnp.asarray(a) for kk, a in cl.items()}
            for cl in state["ring"]
        ]
        (self._caches, self._tok, self._pos, self._done,
         self._keys) = self._place_p(
            self._caches, ring, self._tok, self._pos, self._done,
            self._keys, jnp.asarray(self._pt_host[s]),
            jnp.int32(s), jnp.int32(state["tok"]),
            jnp.int32(state["pos"]), rkey,
        )
        n_cover = min(state["n_cover"], n_pages)
        for j in range(n_cover):
            self.pool.register(state["digests"][j], pids[j],
                               volatile=wraps)
        self._slot_req[s] = req
        if req.admitted_tick is None:
            req.admitted_tick = self.tick_count
        if self._trace is not None \
                and getattr(req, "trace", None) is not None:
            self._trace.event(
                req.trace, "admitted", time.perf_counter(),
                tick=self.tick_count, adopted=True,
            )
        return req

    def run(self, max_ticks: int = 10_000) -> None:
        """Tick until every queued and in-flight request retires."""
        for _ in range(max_ticks):
            if self.pending == 0 and self.active == 0:
                return
            self.step()
        raise RuntimeError(
            f"not drained after {max_ticks} ticks: {self.pending} "
            f"queued, {self.active} active"
        )

    # -- admission ------------------------------------------------------

    def _admit_from_queue(self, retired: list[Request]) -> None:
        free = [s for s, r in enumerate(self._slot_req) if r is None]
        if self._drr is not None:
            self._admit_drr(free, retired)
            return
        while self._queue and free:
            plan = None
            if self.paged:
                plan = self._plan_pages(self._queue[0])
                if plan is None:
                    # head-of-line request does not fit the page
                    # budget: admission waits for retirements to
                    # return pages (FIFO — no reordering, so a large
                    # request cannot be starved by later small ones;
                    # the qos= DRR hook above is the per-TENANT
                    # alternative, where only that tenant's queue
                    # defers and the rotation tries the next)
                    break
            s = free.pop(0)
            req = self._queue.popleft()
            self._admit_into(s, req, plan, retired)

    def _admit_drr(self, free: list[int],
                   retired: list[Request]) -> None:
        """QoS admission: free slots are filled in deficit-round-robin
        order (:class:`~..qos.DeficitScheduler` — weighted,
        work-conserving, deficits carried). A tenant whose head cannot
        be PLANNED right now (page-pool pressure, or its page quota
        even after reclaiming its own cold pages) is restored
        unchanged and the rotation passes over that TENANT for the
        rest of this pass — one tenant's unplannable head never blocks
        another tenant's admission, which is the head-of-line
        decoupling FIFO cannot give."""
        deferred: set[str] = set()
        while free:
            pick = self._drr.pick(skip=deferred)
            if pick is None:
                return
            tenant, req, cost = pick
            plan = None
            if self.paged:
                plan = self._plan_pages_qos(req)
                if plan is None:
                    self._drr.restore(tenant, req, cost)
                    deferred.add(tenant)
                    continue
            s = free.pop(0)
            self._admit_into(s, req, plan, retired)

    def _admit_into(self, s: int, req: Request, plan,
                    retired: list[Request]) -> None:
        """Install one dequeued request into free slot ``s`` (the
        admission body both the FIFO and DRR paths share); ``plan`` is
        the committed-page plan on paged schedulers, None otherwise."""
        Tp = req.prompt.size
        base = 0
        admit_kw: dict[str, Any] = {}
        if self.paged:
            base, admit_kw = self._commit_pages(req, plan)
        rem = Tp - base
        n_chunks = -(-rem // self.C)
        padded = np.zeros((1, n_chunks * self.C), np.int32)
        padded[0, :rem] = req.prompt[base:]
        cache = _fresh_cache(self.cfg, 1, self.Lmax,
                             self.quantize_kv)
        if base:
            # skip the shared prefix's prefill outright: its K/V
            # seed the transient cache from the resident pages
            # (identical bytes to what this prefill would compute)
            cache = self._seed(
                cache, self._caches,
                jnp.asarray(admit_kw["pids"], jnp.int32),
                jnp.int32(base),
            )
        self._slot_req[s] = req
        self._admitting[s] = _Admitting(
            req, cache, jnp.asarray(padded), n_chunks, base=base,
            **admit_kw,
        )
        req.admitted_tick = self.tick_count
        if self._obs is not None and req.tenant is not None:
            self._obs.qos_admitted(self, req.tenant)
        if self._trace is not None and req.trace is not None:
            self._trace.event(
                req.trace, "admitted", time.perf_counter(),
                tick=self.tick_count,
            )
        # first chunk runs this very tick (short prompts admit in
        # one tick and decode from the next)
        self._advance_admission(s, retired)

    # -- paged admission planning --------------------------------------

    def _plan_pages(self, req: Request):
        """Page budget for ``req``: which resident prefix pages it can
        share, how many fresh pages it needs, and how many COW
        reservations the shares must attach (one per share that can
        ever end in a write — the sharer wraps its ring, or the page's
        owner does). Returns None when the pool cannot cover the plan
        — the caller leaves the request queued.

        The budget is the request's whole lifetime upper bound: ring
        slots ``[0, min(W, Tp + max_new + n_inner))`` — prefill plus
        every decode write including the bounded overshoot of the
        retirement tick — so :class:`PagePoolExhausted` is unreachable
        mid-decode (the capacity contract the fuzz tests pin)."""
        shared, digests, n_pages, wraps, n_fresh, reserve, fetch = \
            self._page_needs(req)
        if not self.pool.can_alloc(n_fresh, reserve=reserve):
            return None
        return (shared, digests, n_pages, wraps, fetch)

    def _page_needs(self, req: Request):
        """The share walk + budget arithmetic both planners share:
        (shared, digests, n_pages, wraps, n_fresh, reserve, fetch),
        computed WITHOUT consulting pool capacity —
        :meth:`_plan_pages` checks ``can_alloc`` and
        :meth:`_plan_pages_qos` turns the same numbers into a reclaim
        shortfall instead. ``fetch`` is the fleet-cache extension:
        where the LOCAL share walk breaks, the walk continues against
        the fleet directory (host-DRAM store / peer replicas), and
        every contiguously-probeable digest becomes a planned fetch —
        a fresh allocation whose prefill is replaced by a page copy.
        Budget-wise fetched pages ARE fresh pages (they are inside
        ``n_fresh``), so the capacity/quota arithmetic is unchanged;
        only the prefill skip differs, and a fetch that fails at
        commit time degrades to exactly the prefill the plan budgeted
        for."""
        Tp = req.prompt.size
        W, P = self.W, self.P
        digests: list[bytes] = []
        shared: list[int] = []
        fetch: list[bytes] = []
        if Tp <= W:
            # within-window prompts: ring slot s == position s, so the
            # page content is determined by the page-aligned prefix —
            # the shareable case. (A wrapped prompt's pages hold late
            # positions; they are neither shared nor registered.)
            digests = prefix_page_digests(req.prompt, P, self.max_pages)
            # cap: at least the prompt's last token must prefill (the
            # first sampled token needs its logits)
            shareable = digests[: (Tp - 1) // P]
            for d in shareable:
                pid = self.pool.lookup(d)
                if pid is None:
                    break
                shared.append(pid)
            if self.cache is not None:
                for d in shareable[len(shared):]:
                    if self.cache.probe(
                            d, exclude=self.cache_name) is None:
                        break
                    fetch.append(d)
        m = len(shared)
        horizon = Tp + req.max_new + self.n_inner
        wraps = horizon > W
        n_pages = -(-min(W, horizon) // P)
        reserve = sum(
            1 for pid in shared
            if self.pool.share_needs_reserve(pid, wraps)
        )
        return (shared, digests, n_pages, wraps, n_pages - m, reserve,
                fetch)

    def _commit_pages(self, req: Request, plan) -> tuple[int, dict]:
        """Execute an admission plan: take references on the shared
        pages (attaching their COW reservations), FETCH the planned
        fleet-cache pages (host DRAM or a peer replica — each fetched
        page is a fresh allocation filled with the transferred bytes
        and registered, extending the prefill skip past the local
        share run), and allocate the fresh tail. A fetch that comes
        back empty (eviction, partition, kill raced the plan) stops
        the fetch run and the remaining pages prefill as budgeted —
        the cache saves work or does nothing, never corrupts.
        Returns (base, _Admitting kwargs)."""
        shared, digests, n_pages, wraps, fetch = plan
        m = len(shared)
        pids = [NULL_PAGE] * self.max_pages
        for j, pid in enumerate(shared):
            self.pool.share(
                pid, reserve=self.pool.share_needs_reserve(pid, wraps),
                wrapper=wraps,
            )
            pids[j] = pid
            if self._trace is not None and req.trace is not None:
                self._trace.event(
                    req.trace, "share_hit", time.perf_counter(),
                    page=int(pid),
                )
            if self._drr is not None and pid in self._cold:
                # a cold page found its next sharer: the cache's hold
                # transfers to the new slot (warm)
                self._warm_cold(pid)
        n_fetched = 0
        for d in fetch:
            got = self.cache.fetch(d, exclude=self.cache_name)
            if got is None:
                break  # fall back to prefill for the rest of the run
            src, payload = got
            pid = self.pool.alloc()
            self._install_page_payload(pid, payload)
            # first-wins: if a concurrent admission registered the
            # digest since planning, this is a no-op and the page is
            # simply this slot's private copy — still correct bytes
            self.pool.register(d, pid, volatile=wraps)
            pids[m + n_fetched] = pid
            n_fetched += 1
            if self._obs is not None:
                self._obs.fleet_hit(src)
            if self._trace is not None and req.trace is not None:
                self._trace.event(
                    req.trace, "share_hit", time.perf_counter(),
                    page=int(pid), tier=src,
                )
        m += n_fetched
        for j in range(m, n_pages):
            pids[j] = self.pool.alloc()
        if self._drr is not None and req.tenant is not None:
            self._tenant_pages[req.tenant] = (
                self._tenant_pages.get(req.tenant, 0) + n_pages
            )
        # pages fully covered by the prompt hold registerable prefix
        # content once prefill lands them (done at finish)
        n_cover = min(req.prompt.size // self.P, self.max_pages) \
            if req.prompt.size <= self.W else 0
        return m * self.P, {
            "pids": pids, "digests": tuple(digests),
            "n_cover": n_cover, "wraps": wraps,
        }

    # -- QoS page quotas + cold-page reclaim (qos= only) ----------------
    #
    # A retiring request's still-registered refcount-1 prefix pages go
    # COLD instead of freeing: resident for future sharers (their
    # digests stay in the pool's table, their bytes untouched in the
    # arena — nothing writes a page no slot's table names), attributed
    # to the departing tenant, and evictable. Reclaim is COW-aware by
    # construction: cold pages have refcount 1 (a cold page that gains
    # a sharer is warmed out of the cold set first), so eviction can
    # never touch a page a live holder reads — a shared prefix page is
    # never yanked from under a compliant co-holder.

    def _tenant_usage(self, tenant: str) -> int:
        """Pages attributed to the tenant: hot refs held by its
        resident slots + its cold pages. The quota number."""
        return (self._tenant_pages.get(tenant, 0)
                + self._cold_count.get(tenant, 0))

    def _tenant_debit(self, tenant: str | None, n: int) -> None:
        if self._drr is None or tenant is None or n == 0:
            return
        left = self._tenant_pages.get(tenant, 0) - n
        if left:
            self._tenant_pages[tenant] = left
        else:
            self._tenant_pages.pop(tenant, None)

    def _over_quota(self, tenant: str) -> bool:
        if tenant not in self._qos:
            return False  # adopted stream from an unregistered tenant
        quota = self._qos.get(tenant).pages
        return quota is not None and self._tenant_usage(tenant) > quota

    def _drop_cold(self, pid: int) -> str:
        """Remove ``pid`` from the cold set — the ONE place the cold
        bookkeeping (set, per-tenant count, the cache's pool hold)
        comes apart, shared by warm and evict. Returns the tenant the
        page was attributed to."""
        t = self._cold.pop(pid)
        n = self._cold_count.get(t, 0) - 1
        if n:
            self._cold_count[t] = n
        else:
            self._cold_count.pop(t, None)
        self.pool.decref(pid)
        return t

    def _warm_cold(self, pid: int) -> None:
        """A cold page gained a holder: drop the cache's hold and the
        tenant attribution (the new holder's refs are the page's life
        now)."""
        self._drop_cold(pid)

    def _evict_cold_page(self, *, protect=frozenset(),
                         tenant: str | None = None) -> bool:
        """Evict ONE cold page — the reclaim primitive. ``tenant``
        narrows to that tenant's cold pages (quota enforcement);
        otherwise pool-pressure order: an OVER-QUOTA tenant's cold
        pages first, then any (cold residency is cache, not
        entitlement — deferring live work to preserve a cold page
        would break work conservation). Oldest-first within each
        class; ``protect`` pins pages the in-flight plan would share.
        Returns False when nothing evictable remains."""
        victim = None
        if tenant is not None:
            for pid, t in self._cold.items():
                if t == tenant and pid not in protect:
                    victim = pid
                    break
        else:
            for pid, t in self._cold.items():
                if pid not in protect and self._over_quota(t):
                    victim = pid
                    break
            if victim is None:
                for pid in self._cold:
                    if pid not in protect:
                        victim = pid
                        break
        if victim is None:
            return False
        if self.cache is not None:
            # the evicted cold page's last HBM incarnation dies here:
            # spill its bytes to the DRAM tier (tenant-attributed, so
            # spill_pages quotas bind) before the freeing decref
            self._spill_page(victim, tenant=self._cold.get(victim))
        t = self._drop_cold(victim)
        if self._flight is not None:
            self._flight.event(
                "qos reclaim", src="scheduler", tenant=t, page=victim,
            )
        return True

    def _plan_pages_qos(self, req: Request):
        """:meth:`_plan_pages` under the tenant's page quota, with
        cold-page reclaim on both pressure paths: pool exhaustion
        evicts exactly the shortfall in cold pages (over-quota
        tenants' first, oldest-first); quota exhaustion evicts the
        requesting tenant's OWN cold pages. Returns None when the
        request still cannot be planned — the DRR pass then defers
        this tenant, not the rotation."""
        contract = self._qos.get(req.tenant)
        shared, digests, n_pages, wraps, n_fresh, reserve, fetch = \
            self._page_needs(req)
        # the plan's own shares are never reclaim victims: evicting
        # one to make room would trade a prefill skip for a fresh
        # page — strictly worse on both bytes and time. (A resident
        # page the plan cannot share gives no skip and stays an
        # honest eviction candidate.)
        protect = set(shared)
        # pool pressure: can_alloc is `n_fresh + reserve + reserved
        # <= free`, and an evicted cold page (refcount 1, zero
        # reservations by construction) frees exactly one page — so
        # the shortfall is computed ONCE and reclaimed in one pass,
        # never replanned (the protect set keeps the share walk
        # valid across evictions)
        shortfall = (n_fresh + reserve + self.pool.reserved
                     - self.pool.free)
        for _ in range(max(shortfall, 0)):
            if not self._evict_cold_page(protect=protect):
                return None
        if contract.pages is not None:
            own_cold_shared = sum(
                1 for pid in shared
                if self._cold.get(pid) == req.tenant
            )
            # sharing one's own cold page moves it cold -> hot: no new
            # usage; everything else is net-new attribution
            need = (self._tenant_usage(req.tenant) + n_pages
                    - own_cold_shared)
            while need > contract.pages:
                if not self._evict_cold_page(protect=protect,
                                             tenant=req.tenant):
                    return None
                need -= 1
        return (shared, digests, n_pages, wraps, fetch)

    def _prepare_tick_pages(self, decoding: list[int]) -> None:
        """Pre-tick COW pass: the next ``n_inner`` decode steps write
        ring slots ``[pos, pos + n_inner)`` (mod W) of every decoding
        row. Any touched page still shared (refcount > 1) is copied to
        a fresh page, consuming the reservation attached to the shared
        page at admission (``PagePool.cow_alloc``); a touched page
        this slot owns outright but once REGISTERED as a prefix drops
        out of the share table (its bytes are about to change). After
        this pass the device scan only ever writes exclusively-owned
        pages — COW is invisible to the compiled program."""
        copies: list[tuple[int, int]] = []
        for s in decoding:
            pos = self._host_pos[s]
            touched = {
                ((pos + t) % self.W) // self.P
                for t in range(self.n_inner)
            }
            for j in sorted(touched):
                pid = int(self._pt_host[s, j])
                if pid == NULL_PAGE:
                    # defensive: the admission budget allocates every
                    # touchable page eagerly, so this is unreachable
                    # unless the budget math regressed
                    raise PagePoolExhausted(
                        f"slot {s} page {j} unallocated at write time "
                        "(admission budget bug)"
                    )
                if self.pool.refcount(pid) > 1:
                    new = self.pool.cow_alloc(pid)
                    copies.append((pid, new))
                    if self._trace is not None:
                        _r = self._slot_req[s]
                        if _r is not None and _r.trace is not None:
                            self._trace.event(
                                _r.trace, "cow_copy",
                                time.perf_counter(), page=int(pid),
                            )
                    # the writer leaves the shared page for its copy;
                    # only wrapping slots ever write shared pages, so
                    # the page's wrapper count drops with it
                    self.pool.decref(pid,
                                     wrapper=self._slot_wraps[s])
                    self._pt_host[s, j] = new
                    self._pt_dev = None
                else:
                    self.pool.note_write(pid)
        if copies:
            # one device call for the whole tick's copies; pad to a
            # power of two with null-page self-copies so the jitted
            # program compiles O(log) distinct shapes, not one per
            # divergence count
            n = 1 << (len(copies) - 1).bit_length()
            copies += [(NULL_PAGE, NULL_PAGE)] * (n - len(copies))
            src, dst = (np.asarray(c, np.int32) for c in zip(*copies))
            self._caches = self._copy(
                self._caches, jnp.asarray(src), jnp.asarray(dst)
            )

    def _advance_admissions(self, retired: list[Request]) -> None:
        for s in list(self._admitting):
            self._advance_admission(s, retired)

    def _advance_admission(self, s: int,
                           retired: list[Request]) -> None:
        st = self._admitting[s]
        i = st.next_chunk
        chunk = jax.lax.dynamic_slice_in_dim(
            st.padded, i * self.C, self.C, axis=1
        )
        st.last_logits, st.cache = self._extend(
            self.params, chunk, st.cache, jnp.int32(st.base + i * self.C)
        )
        st.next_chunk += 1
        if self._obs is not None:
            self._obs.prefill_chunk()
        if self._trace is not None and st.req.trace is not None:
            self._trace.event(
                st.req.trace, "prefill_chunk", time.perf_counter(),
                tick=self.tick_count,
            )
        if st.next_chunk < st.n_chunks:
            return
        Tp = st.req.prompt.size
        rkey = (st.req.key if st.req.key is not None
                else jax.random.key(st.req.id + 1))
        tok0, ring = self._finish(
            st.cache, st.last_logits, jnp.int32(Tp),
            jnp.int32(st.base + (st.n_chunks - 1) * self.C), rkey,
        )
        if self.paged:
            # install the page table NOW (stale row writes landed in
            # the null page until this point), then scatter the ring
            # window into the pages and flip the row live
            self._pt_host[s] = st.pids
            self._pt_dev = None
            self._host_pos[s] = Tp
            self._slot_wraps[s] = st.wraps
            (self._caches, self._tok, self._pos, self._done,
             self._keys) = self._place_p(
                self._caches, ring, self._tok, self._pos, self._done,
                self._keys, jnp.asarray(self._pt_host[s]),
                jnp.int32(s), tok0, jnp.int32(Tp), rkey,
            )
            # the prompt-covered pages now hold exactly the content
            # their chained prefix digests describe — publish them for
            # future admissions to share (first-wins; the shared ones
            # are already registered)
            for j in range(st.n_cover):
                self.pool.register(st.digests[j], st.pids[j],
                                   volatile=st.wraps)
        else:
            (self._caches, self._tok, self._pos, self._done,
             self._keys) = self._place(
                self._caches, ring, self._tok, self._pos, self._done,
                self._keys, jnp.int32(s), tok0, jnp.int32(Tp), rkey,
            )
        st.req.tokens.append(int(tok0))
        if self._obs is not None:
            self._obs.first_token(st.req, time.perf_counter())
        if self._trace is not None and st.req.trace is not None:
            self._trace.event(
                st.req.trace, "first_token", time.perf_counter(),
                tick=self.tick_count,
            )
        del self._admitting[s]
        if self._retire_if_due(st.req):  # max_new == 1 or prompt EOS
            self._free_slot(s)
            retired.append(st.req)

    # -- retirement -----------------------------------------------------

    def _retire_if_due(self, req: Request) -> bool:
        cut = None
        if self.eos_id is not None and req._eos_at is None:
            # scan only this tick's new tokens (a long-lived request
            # must not pay a full-history scan per tick)
            try:
                req._eos_at = req.tokens.index(
                    self.eos_id, req._scanned
                )
            except ValueError:
                pass
            req._scanned = len(req.tokens)
        if req._eos_at is not None:
            cut = req._eos_at + 1
            if cut <= req.max_new:
                req.reason = "eos"
            else:
                cut = None
        if cut is None and len(req.tokens) >= req.max_new:
            cut = req.max_new
            req.reason = "length"
        if cut is None:
            return False
        del req.tokens[cut:]
        req.finished = True
        req.retired_tick = self.tick_count
        # owner-only terminal stamp (see _retire_cancelled)
        if self._trace is not None and req.trace is not None \
                and req._trace_owned:
            self._trace.event(
                req.trace, "retired", time.perf_counter(),
                outcome=req.reason, tokens=len(req.tokens),
            )
        return True

    def _free_slot(self, s: int) -> None:
        req = self._slot_req[s]
        self._slot_req[s] = None
        # the row keeps decoding garbage until reused — done=True makes
        # it emit EOS-clamped tokens nobody reads; admission resets it
        self._done = self._done.at[s].set(True)
        if self.paged:
            # return the slot's pages (shared prefixes just drop one
            # reference; a page frees — and leaves the prefix table —
            # only when its last reader retires) and null the row so
            # its zombie writes land in the null page. Under qos=, a
            # sole-held page whose prefix digest is still registered
            # goes COLD instead of freeing (the reclaim contract
            # above): resident for future sharers, attributed to the
            # departing tenant, evicted oldest-first under pressure.
            tenant = (req.tenant if self._drr is not None
                      and req is not None else None)
            keep_cold = tenant is not None and not self._slot_wraps[s]
            n_refs = 0
            for pid in self._pt_host[s]:
                if pid == NULL_PAGE:
                    continue
                pid = int(pid)
                n_refs += 1
                if (keep_cold and self.pool.refcount(pid) == 1
                        and self.pool.registered(pid)):
                    self._cold[pid] = tenant
                    self._cold_count[tenant] = (
                        self._cold_count.get(tenant, 0) + 1
                    )
                else:
                    # fleet spill: a sole-held registered page is
                    # about to free (and leave the share table) —
                    # offer its bytes to the DRAM tier first, so a
                    # sibling's future admission fetches instead of
                    # re-prefilling. Cold retention above takes
                    # precedence (HBM residency beats DRAM); eviction
                    # of the cold set spills on its own path.
                    if (self.cache is not None
                            and not self._slot_wraps[s]
                            and self.pool.refcount(pid) == 1
                            and self.pool.registered(pid)):
                        self._spill_page(pid, tenant=tenant)
                    self.pool.decref(pid,
                                     wrapper=self._slot_wraps[s])
            self._tenant_debit(tenant, n_refs)
            self._pt_host[s] = NULL_PAGE
            self._pt_dev = None
            self._host_pos[s] = 0
            self._slot_wraps[s] = False
