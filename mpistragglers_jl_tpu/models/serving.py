"""Continuous batching: a multi-request serving scheduler.

The reference is transport-only (src/MPIAsyncPools.jl:1-226 — no model,
no serving); this is north-star serving scope (VERDICT r4 next-#1),
converting the round-4 serving inventory (ring cache, GQA decode, int8
KV, speculative/hedged) from single-request features into aggregate
throughput. At B=1 a decode step is weight-read-bound — the HBM traffic
is the parameters, amortized over one token (docs/PERF.md). Batching S
concurrent requests into one step amortizes the same weight reads over
S tokens; until the KV-cache reads dominate, aggregate tokens/s scales
near-linearly with S. That economics is the whole point of this module.

Design (TPU-first):

* **Fixed slots, static shapes.** The scheduler owns ``S`` serving
  slots. Per-layer state is ONE batched O(W) ring cache
  ``(S, W, kv_heads, head_dim)`` — the ring layout
  (models/decode.py) makes every slot a fixed-size arena regardless of
  how long its request runs, so slot reuse is a row overwrite, never a
  reallocation, and one compiled program serves every scheduler tick.
* **Per-row positions.** Unlike ``decode_step_ring_dense`` (one scalar
  position for the whole batch), every slot decodes at its own global
  position: RoPE angles, ring-slot writes, and the ``kpos >= 0``
  validity mask are all computed per row (``_rope_rows``,
  ``_ring_write_rows``, ``_ring_attention_rows``). The masks make slot
  reuse safe: a freshly admitted row's unwritten slots have
  ``kpos < 0`` and self-mask, so the previous occupant's K/V are
  unreachable even before they are overwritten.
* **Inner scan, host ticks.** Each scheduler tick runs ``n_inner``
  decode steps for all S slots inside one ``lax.scan`` program — one
  host round trip per ``S x n_inner`` tokens (on the tunneled bench
  chip a round trip costs ~120 ms; per-token host control would bury
  the batching win).
* **Chunked prefill interleaved with decode.** Admission does not
  stall in-flight requests behind a long prompt: each tick advances
  every admitting request by ONE C-token prefill chunk (through the
  masked cached-attention path, exactly ``make_extend``'s semantics)
  and then runs the decode scan. With ``quantize_kv=True`` each chunk
  attends the already-quantized cache — the only math available once
  earlier chunks' raw K/V are gone — and per-position absmax
  quantization makes the chunk size invisible, so the stream is
  IDENTICAL at any ``prompt_chunk`` and equals the quantized oracle
  (``generate_ring_dense(quantize_kv=True)``, whose prefill runs the
  same cached-attention math — ADVICE r5 repaired in PR 1; both the
  identity and its chunk-invariance premise are pinned by
  tests/test_serving.py). A request's prefill lands in a
  transient positional cache; on the last chunk the final-W window
  gathers into its slot's ring rows (``ring_from_cache`` math with a
  traced length) and the first token comes from the last chunk's
  logits. Decode stall per tick is bounded by one chunk, not one
  prompt.
* **EOS retirement + slot reuse.** Rows that emit ``eos_id`` keep
  emitting it on-device (static shapes; ``_eos_clamp``); the host
  strips the tail, retires the request (EOS or its ``max_new`` budget),
  and hands the slot to the next queued request.

Greedy decoding per row equals the single-request oracle
(:func:`~.decode.generate_ring_dense`) token-for-token — the batched
per-row step is the same math evaluated at S independent (row,
position) points; tests/test_serving.py pins every admitted request
against its oracle stream, including staggered admissions and reuse.
One precision caveat: "same math" means same at exact f32 — at the
TPU's DEFAULT matmul precision (bf16 MXU passes) the batched and
single-request program shapes round differently and greedy argmax
TIES can flip between them (set
``jax.config.update("jax_default_matmul_precision", "highest")`` for
cross-shape exactness; examples/continuous_batching.py demonstrates).

``make_serving_scan(cfg, mesh=...)`` is the sharded variant of the
decode tick (slots over ``dp``, heads over ``tp``, the training path's
psum placement) — the multi-chip serving program the driver dryrun
compiles and checks against the dense tick.
"""

from __future__ import annotations

import functools
import time
from collections import deque
from typing import Any, Sequence

import numpy as np

import jax
from .. import _jax_compat  # noqa: F401  (installs older-JAX aliases)
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..obs.timeline import annotate as _annotate
from .decode import (
    _NEG,
    _cache_pv,
    _cache_scores,
    _check_ring_cfg,
    _check_sampling_params,
    _decode_kernel_enabled,
    _decode_kernel_interpreted,
    _UNSET,
    _eos_clamp,
    _incremental_forward,
    _is_quantized,
    _kernel_possible,
    _kernel_viable,
    _kv_quantize,
    _pick_token,
    _ring_from_cache,
    _route_kernel,
)
from .transformer import (
    TransformerConfig,
    _ln,
    _mlp,
    make_kv_slice,
    param_specs,
)

__all__ = [
    "Request",
    "ServingScheduler",
    "make_serving_scan",
    "serving_decode_step_dense",
]


def _fresh_cache(cfg: TransformerConfig, B: int, L: int,
                 quantize_kv: bool = False) -> list[dict]:
    """Zeroed positional/ring cache with DISTINCT buffers per leaf.
    decode.py's ``_zero_cache_layer`` aliases one zeros array for k and
    v (fine undonated); the serving programs donate their caches, and
    donating the same buffer twice is an XLA execution error."""
    shape = (B, L, cfg.kv_heads, cfg.head_dim)
    kvdt = jnp.int8 if quantize_kv else cfg.dtype

    def layer():
        out = {"k": jnp.zeros(shape, kvdt), "v": jnp.zeros(shape, kvdt)}
        if quantize_kv:
            out["k_s"] = jnp.zeros(shape[:3], jnp.float32)
            out["v_s"] = jnp.zeros(shape[:3], jnp.float32)
        return out

    return [layer() for _ in range(cfg.n_layers)]


# --------------------------------------------------------------------------
# per-row primitives (each slot at its own global position)
# --------------------------------------------------------------------------


def _rope_rows(x, pos):
    """Rotary embedding for single-token rows: x (S, 1, H, D), pos (S,)
    global positions — the per-row counterpart of transformer._rope
    (which shares one position vector across the batch)."""
    Dh = x.shape[-1]
    half = Dh // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]  # (S, half)
    cos = jnp.cos(ang)[:, None, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, None, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )


def _ring_write_rows(cache_l: dict, k, v, slot):
    """Write each row's single-token K/V at its own ring slot:
    k, v (S, 1, Hkv, D), slot (S,) — a per-row scatter on the slot
    axis (decode.py's ``_cache_write`` writes one shared offset)."""
    rows = jnp.arange(k.shape[0])

    def put(c, u):
        return c.at[rows, slot].set(u[:, 0].astype(c.dtype))

    if not _is_quantized(cache_l):
        return {"k": put(cache_l["k"], k), "v": put(cache_l["v"], v)}
    kq, ks = _kv_quantize(k)
    vq, vs = _kv_quantize(v)
    return {
        "k": put(cache_l["k"], kq),
        "v": put(cache_l["v"], vq),
        "k_s": put(cache_l["k_s"], ks),
        "v_s": put(cache_l["v_s"], vs),
    }


def _ring_attention_rows(q, cache_l, pos, scale, use_kernel=False):
    """Single-query ring attention with a per-row position: the same
    ``kpos(s) = pos - ((pos - s) mod W), valid iff kpos >= 0`` invariant
    as decode.py's ``_ring_cached_attention``, evaluated rowwise. The
    mask is simultaneously causal bound, sliding-window bound, warmup
    guard, AND slot-reuse guard (a reused slot's stale rows sit at
    kpos < 0 for the new occupant until overwritten).

    ``use_kernel=True`` routes int8 caches through the Pallas decode
    kernel's ring mode (per-row positions ride SMEM): ONE kernel call
    serves all S slots, so the scan/custom_call boundary cost that
    sinks the kernel at B=1 is paid once per S tokens — the batched
    regime is where int8 finally converts its byte win into time
    (docs/PERF.md). Default False: this function is also the dense
    ORACLE step (``serving_decode_step_dense``), which stays einsum so
    kernel-vs-einsum parity is testable against it."""
    W = cache_l["k"].shape[1]
    if use_kernel and _kernel_viable(q, cache_l):
        from ..ops.decode_attention import quantized_decode_attention

        return quantized_decode_attention(
            q, cache_l, pos, scale, ring=True
        )
    s = _cache_scores(q, cache_l, scale)  # (S, H, 1, W) f32
    kpos = pos[:, None] - jnp.mod(
        pos[:, None] - jnp.arange(W)[None, :], W
    )  # (S, W)
    s = jnp.where((kpos >= 0)[:, None, None, :], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    o = _cache_pv(p, cache_l)
    return o.astype(q.dtype)


def _serving_layer(x, lp, cache_l, pos, cfg, *, kv_slice=None,
                   tp_psum=False, use_kernel=False):
    """One layer of the per-row serving step (the dense-FFN half of
    decode.py's ``_incremental_layer`` with per-row positions)."""
    h = _ln(x, lp["ln1_s"], lp["ln1_b"])
    q = jnp.einsum("bld,dhk->blhk", h, lp["wq"])
    k = jnp.einsum("bld,dhk->blhk", h, lp["wk"])
    v = jnp.einsum("bld,dhk->blhk", h, lp["wv"])
    if kv_slice is not None:
        k, v = kv_slice(k), kv_slice(v)
    q, k = _rope_rows(q, pos), _rope_rows(k, pos)
    W = cache_l["k"].shape[1]
    cache_l = _ring_write_rows(cache_l, k, v, jnp.mod(pos, W))
    o = _ring_attention_rows(q, cache_l, pos, cfg.head_dim ** -0.5,
                             use_kernel=use_kernel)
    attn_out = jnp.einsum("blhk,hkd->bld", o, lp["wo"])
    if tp_psum:
        attn_out = jax.lax.psum(attn_out, "tp")
    x = x + attn_out
    h2 = _ln(x, lp["ln2_s"], lp["ln2_b"])
    y = _mlp(h2, lp)
    if tp_psum:
        y = jax.lax.psum(y, "tp")
    return x + y + lp["b2"], cache_l


def _serving_forward(params, tok, pos, caches, cfg, *, kv_slice=None,
                     tp_psum=False, use_kernel=False):
    """(tok (S,), pos (S,), caches) -> (logits (S, V), caches)."""
    x = params["emb"][tok[:, None]]  # (S, 1, d)
    new = []
    for lp, cl in zip(params["layers"], caches):
        x, cl = _serving_layer(x, lp, cl, pos, cfg, kv_slice=kv_slice,
                               tp_psum=tp_psum, use_kernel=use_kernel)
        new.append(cl)
    x = _ln(x, params["lnf_s"], params["lnf_b"])
    logits = jnp.einsum("bld,vd->blv", x, params["emb"])
    return logits[:, 0], new


def serving_decode_step_dense(params, tok, pos, caches,
                              cfg: TransformerConfig):
    """One batched serving decode step, dense: every slot at its own
    position. Returns (logits (S, V), caches). The single-position
    sibling is :func:`~.decode.decode_step_ring_dense`. Always the
    einsum path — this is the reference step the kernelized tick is
    pinned against."""
    _check_ring_cfg(cfg)
    return _serving_forward(params, tok, pos, caches, cfg)


def _pick_rows(lg, pos, keys, temperature, top_k, dtype):
    """Per-row token choice: greedy at temperature 0 (static), else
    per-row keyed sampling — each row evaluated as row 0 of its own
    B=1 stream THROUGH ``decode._pick_token`` itself (vmapped), so the
    fold/truncation discipline has one source of truth and a slot's
    sampled stream equals ``generate_ring_dense(..., key=key_row)``
    for the same request key by construction."""
    if temperature == 0.0:
        return jnp.argmax(lg, axis=-1).astype(dtype)
    return jax.vmap(
        lambda k, p, ll: _pick_token(
            ll[None], p, k, temperature, top_k, dtype
        )[0]
    )(keys, pos, lg)


def _scan_body(params, tok, pos, done, caches, cfg, eos_id, n_inner,
               keys, *, temperature=0.0, top_k=None,
               kv_slice=None, tp_psum=False, use_kernel=False):
    """``n_inner`` decode steps for all S slots under one scan (greedy,
    or per-row keyed sampling when ``temperature > 0``; ``keys`` is
    required — a silent shared-default key would couple every
    scheduler's streams).
    Returns (tok, pos, done, caches, toks (S, n_inner))."""

    def step(carry, _):
        tok, pos, done, caches = carry
        lg, caches = _serving_forward(
            params, tok, pos, caches, cfg, kv_slice=kv_slice,
            tp_psum=tp_psum, use_kernel=use_kernel,
        )
        nxt = _pick_rows(lg, pos, keys, temperature, top_k, tok.dtype)
        nxt, done = _eos_clamp(nxt, tok, done, eos_id)
        return (nxt, pos + 1, done, caches), nxt

    (tok, pos, done, caches), toks = jax.lax.scan(
        step, (tok, pos, done, caches), None, length=n_inner
    )
    return tok, pos, done, caches, toks.swapaxes(0, 1)


@functools.lru_cache(maxsize=32)
def _serving_scan_dense(cfg: TransformerConfig, n_inner: int,
                        eos_id: int | None, temperature: float = 0.0,
                        top_k: int | None = None,
                        use_kernel: bool = False):
    """Jitted dense tick: (params, tok, pos, done, caches, keys) ->
    (tok, pos, done, caches, toks). Caches donated — the tick updates
    the arena in place in HBM. ``use_kernel`` is the scheduler's
    RESOLVED int8-kernel routing (part of the cache key, so toggling
    the global routes on the next scheduler construction)."""

    @functools.partial(jax.jit, donate_argnums=(4,))
    def run(params, tok, pos, done, caches, keys):
        return _scan_body(params, tok, pos, done, caches, cfg, eos_id,
                          n_inner, keys, temperature=temperature,
                          top_k=top_k, use_kernel=use_kernel)

    return run


def make_serving_scan(cfg: TransformerConfig, mesh: Mesh, n_inner: int,
                      *, eos_id: int | None = None,
                      quantize_kv: bool = False,
                      temperature: float = 0.0,
                      top_k: int | None = None):
    """Sharded serving tick: slots over ``dp``, heads over ``tp``
    (psum placement of the training path — the serving counterpart of
    :func:`~.decode.make_decode_step` with per-row positions).
    Returns ``f(params, tok, pos, done, caches, keys)`` jitted over
    ``mesh`` with the caches donated (``keys``: per-slot PRNG keys,
    used only at ``temperature > 0``). ``quantize_kv=True`` serves an int8 ring
    cache (scale leaves shard like their K/V; the per-row write/score
    paths detect the layout)."""
    _check_ring_cfg(cfg)
    _check_sampling_params(temperature, top_k)
    if cfg.n_experts:
        raise ValueError(
            "serving scheduler covers dense-FFN configs; MoE decode "
            "routes per chunk (models/decode.py prefill caveat) and is "
            "served via make_generate"
        )
    tp = int(mesh.shape["tp"])
    if cfg.kv_heads % tp != 0 and tp % cfg.kv_heads != 0:
        raise ValueError(
            f"kv_heads {cfg.kv_heads} and tp {tp} must nest (one "
            "divide the other) for the sharded serving tick's cache "
            "layout"
        )
    # kv_heads < tp uses decode.py's replicated-groups layout: the
    # cache's global head axis has `tp` slots, slot t holding kv head
    # t*kv_heads//tp (each device computes its slot locally from the
    # tp-replicated K/V projections via make_kv_slice — no extra
    # collectives). Callers size the cache head axis with
    # `_cache_heads_global(cfg, mesh)` exactly like make_ring_generate.
    cspec = P("dp", None, "tp", None)
    layer_spec = {"k": cspec, "v": cspec}
    if quantize_kv:
        sspec = P("dp", None, "tp")
        layer_spec["k_s"], layer_spec["v_s"] = sspec, sspec
    cspecs = [dict(layer_spec) for _ in range(cfg.n_layers)]
    # make-time snapshot of the int8-kernel toggle (decode.py's
    # discipline: routing and check_vma must come from one reading)
    use_kernel = _decode_kernel_enabled()

    def local(params, tok, pos, done, caches, keys):
        # resolve at this shard's slot count: one ring-kernel call per
        # layer serves every local slot, so the auto gate compares the
        # per-call boundary cost against S_local amortizing rows
        routed = (
            _kernel_possible(cfg, quantize_kv, use_kernel)
            and _route_kernel(use_kernel, tok.shape[0])
        )
        return _scan_body(
            params, tok, pos, done, caches, cfg, eos_id, n_inner,
            keys, temperature=temperature, top_k=top_k,
            kv_slice=make_kv_slice(cfg), tp_psum=True,
            use_kernel=routed,
        )

    f = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs(cfg, mesh), P("dp"), P("dp"), P("dp"),
                  cspecs, P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp"), cspecs,
                   P("dp", None)),
        # quantize_kv + the kernel toggle routes the int8 ring kernel
        # inside the tick — interpreted Pallas needs the same vma
        # carve-out as decode.py's make_decode_step; einsum-only
        # programs keep varying-axes checking on
        check_vma=not _decode_kernel_interpreted(cfg, quantize_kv,
                                                 use_kernel),
    )
    return jax.jit(f, donate_argnums=(4,))


# --------------------------------------------------------------------------
# admission programs (chunked prefill -> ring window -> slot)
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _extend_chunk_dense(cfg: TransformerConfig, C: int, Lmax: int):
    """One C-token prefill chunk into a (1, Lmax) transient positional
    cache at dynamic ``offset`` (make_extend semantics, dense B=1).
    Cache donated: chunks stream through one arena."""

    @functools.partial(jax.jit, donate_argnums=(2,))
    def run(params, chunk, cache, offset):
        logits, cache = _incremental_forward(
            params, chunk, cache, offset, cfg, prefill=False
        )
        return logits, cache

    return run


@functools.lru_cache(maxsize=32)
def _finish_admit_dense(cfg: TransformerConfig, Lmax: int,
                        temperature: float = 0.0,
                        top_k: int | None = None):
    """Gather the last-W window of a filled positional cache into ring
    rows + pick the first token (greedy, or sampled with the request's
    key at the prompt's last position — decode.py's fold discipline):
    (cache, last_logits (1, C, V), true_len, last_off, key) ->
    (tok0 (), ring leaves (1, W, ...))."""
    W = _check_ring_cfg(cfg)

    @jax.jit
    def run(cache, last_logits, true_len, last_off, key):
        ring = [_ring_from_cache(cl, true_len, W) for cl in cache]
        lg = jnp.take(last_logits[0], true_len - 1 - last_off, axis=0)
        tok0 = _pick_rows(
            lg[None], (true_len - 1)[None], key[None], temperature,
            top_k, jnp.int32,
        )[0]
        return tok0, ring

    return run


@functools.lru_cache(maxsize=32)
def _place_dense(cfg: TransformerConfig):
    """Install an admitted request into slot ``s``: ring rows into the
    batched cache, first token + start position into the row state.
    Everything donated — admission is an in-place row write."""

    @functools.partial(jax.jit, donate_argnums=(0, 2, 3, 4))
    def run(caches, ring, tok, pos, done, keys, s, tok0, pos0, key):
        caches = [
            {kk: c[kk].at[s].set(r[kk][0].astype(c[kk].dtype))
             for kk in c}
            for c, r in zip(caches, ring)
        ]
        return (caches, tok.at[s].set(tok0), pos.at[s].set(pos0),
                done.at[s].set(False), keys.at[s].set(key))

    return run


# --------------------------------------------------------------------------
# observability (obs/ registry + timeline, strictly opt-in)
# --------------------------------------------------------------------------


class _ServingObs:
    """Instrument bundle for one scheduler, resolved ONCE at
    construction so the tick path only increments/observes. Built only
    when a registry or span recorder is attached — a dark scheduler's
    tick does no observability work beyond ``is not None`` checks (the
    tracer's opt-in contract, utils/trace.py), which the no-op
    overhead test in tests/test_obs.py pins.
    """

    def __init__(self, sched: "ServingScheduler", registry, spans):
        self.registry = registry
        self.spans = spans
        self.annotate = _annotate
        # tokens delivered in the CURRENT tick (admission first-tokens
        # + trimmed decode harvest — the same population as
        # serving_tokens_total, so the per-tick rate and the running
        # counter always cross-check)
        self._tick_toks = 0
        self._r = registry is not None
        if not self._r:
            return
        registry.gauge(
            "serving_slots", help="configured serving slots"
        ).set(sched.S)
        self.m_queue = registry.gauge(
            "serving_queue_depth",
            help="requests queued, not yet admitted",
        )
        self.m_active = registry.gauge(
            "serving_active_slots", help="slots decoding or admitting"
        )
        self.m_ticks = registry.counter("serving_ticks_total")
        self.m_tick_s = registry.histogram(
            "serving_tick_seconds", help="scheduler tick wall clock"
        )
        self.m_tokens = registry.counter(
            "serving_tokens_total",
            help="tokens delivered into request streams (first tokens "
            "+ decode harvest, post-retirement trim)",
        )
        self.m_tok_rate = registry.gauge(
            "serving_tokens_per_s",
            help="tokens delivered / tick wall, last tick",
        )
        self.m_ttft = registry.histogram(
            "serving_ttft_seconds", help="submit -> first token"
        )
        self.m_intertoken = registry.histogram(
            "serving_intertoken_seconds",
            help="mean per-token gap, one sample per (slot, tick)",
        )
        self.m_admitted = registry.counter("serving_admitted_total")
        self.m_retired = {
            "eos": registry.counter(
                "serving_retired_total", reason="eos"
            ),
            "length": registry.counter(
                "serving_retired_total", reason="length"
            ),
        }
        self.m_prefill = registry.counter(
            "serving_prefill_chunks_total",
            help="admission prefill chunks advanced",
        )
        # the AUTO gate's resolved decision for THIS scheduler (fixed
        # at construction against its slot count — see use_kernel);
        # incremented once per decode tick, so the series records when
        # the kernel route actually fired, not just that it could
        self.m_route = registry.counter(
            "serving_kernel_route_total",
            help="decode ticks by resolved int8-kernel route",
            route="kernel" if sched.use_kernel else "einsum",
        )

    # -- hooks (each guards its own registry half) ----------------------
    def first_token(self, req: "Request", t: float) -> None:
        self._tick_toks += 1
        if self._r:
            self.m_admitted.inc()
            self.m_tokens.inc()
            if req._t_submit is not None:
                self.m_ttft.observe(t - req._t_submit)
        req._t_last_tok = t

    def tokens_emitted(self, req: "Request", n: int, t: float) -> None:
        self._tick_toks += n
        if self._r:
            self.m_tokens.inc(n)
            last = req._t_last_tok
            if last is not None and n:
                self.m_intertoken.observe((t - last) / n)
        req._t_last_tok = t

    def prefill_chunk(self) -> None:
        if self._r:
            self.m_prefill.inc()

    def tick_done(
        self, sched: "ServingScheduler", retired, t0: float,
        t1: float, t2: float | None,
    ) -> None:
        """t0 tick begin, t1 admissions done, t2 decode scan fetched
        (None when no slot decoded this tick)."""
        t3 = time.perf_counter()
        wall = t3 - t0
        n_toks, self._tick_toks = self._tick_toks, 0
        if self._r:
            self.m_ticks.inc()
            self.m_tick_s.observe(wall)
            self.m_queue.set(sched.pending)
            self.m_active.set(sched.active)
            self.m_tok_rate.set(n_toks / wall if wall > 0 else 0.0)
            if t2 is not None:
                self.m_route.inc()
            for req in retired:
                self.m_retired[req.reason].inc()
        sp = self.spans
        if sp is not None:
            tick = sched.tick_count
            sp.add(
                f"tick {tick}", t0, wall, track="scheduler",
                queue=sched.pending, active=sched.active,
                tokens=n_toks, retired=len(retired),
            )
            sp.add("admit", t0, t1 - t0, track="scheduler")
            if t2 is not None:
                sp.add("decode", t1, t2 - t1, track="scheduler")
                sp.add("retire", t2, t3 - t2, track="scheduler")
            sp.count("queue_depth", sched.pending, t=t3)
            sp.count("active_slots", sched.active, t=t3)


# --------------------------------------------------------------------------
# the scheduler
# --------------------------------------------------------------------------


class Request:
    """One generation request: ``prompt`` (1D int tokens) in,
    ``tokens`` (the generated ids, EOS kept if emitted) out.
    ``finished`` flips at retirement; ``reason`` is ``"eos"`` or
    ``"length"``."""

    _next_id = 0

    def __init__(self, prompt, max_new: int, key=None):
        self.id = Request._next_id
        Request._next_id += 1
        # per-request PRNG key (sampling schedulers); None -> id-derived
        self.key = key
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        self.max_new = int(max_new)
        self.tokens: list[int] = []
        self.finished = False
        self.reason: str | None = None
        # filled by the scheduler: admission tick and retirement tick,
        # the observability hooks the tests and bench read
        self.admitted_tick: int | None = None
        self.retired_tick: int | None = None
        # latency stamps (perf_counter), set only by an instrumented
        # scheduler (registry=/spans=): submit time and last-token time
        self._t_submit: float | None = None
        self._t_last_tok: float | None = None
        # incremental EOS-scan state (scheduler-internal): index of the
        # first EOS if found, and how many tokens were already scanned
        self._eos_at: int | None = None
        self._scanned = 0


class _Admitting:
    """Per-slot chunked-prefill state machine: the transient positional
    cache plus the chunk cursor."""

    def __init__(self, req: Request, cache, padded, n_chunks: int):
        self.req = req
        self.cache = cache
        self.padded = padded  # (1, n_chunks * C) int32
        self.n_chunks = n_chunks
        self.next_chunk = 0
        self.last_logits = None


class ServingScheduler:
    """Continuous-batching scheduler over ``slots`` fixed serving
    slots (dense single-device programs; the sharded tick is
    :func:`make_serving_scan`).

    >>> sched = ServingScheduler(params, cfg, slots=8, eos_id=2)
    >>> r = sched.submit(prompt, max_new=64)   # any time, any order
    >>> sched.run()                            # or step() per tick
    >>> r.tokens                               # greedy == oracle

    Each ``step()`` tick: (1) advance every admitting request by one
    prefill chunk, installing finished ones into their slot; (2) admit
    queued requests into free slots; (3) run ``n_inner`` decode steps
    for all slots in one device program; (4) harvest tokens, retire
    rows that emitted EOS or exhausted their budget, free their slots.
    Greedy by default; ``temperature > 0`` (optionally ``top_k``)
    samples each slot with its request's own key (``submit(...,
    key=...)``; id-derived when omitted) — a sampled stream equals
    ``generate_ring_dense(..., key=request_key)`` exactly, like the
    greedy==oracle contract.

    ``prompt_chunk`` bounds the decode stall a long prompt can inject
    into in-flight requests (one chunk per tick); ``max_prompt`` sizes
    the transient prefill arena (one compile for all prompt lengths).

    Observability is strictly opt-in (the tracer contract): pass
    ``registry=`` (an :class:`~..obs.MetricsRegistry`) for tick/queue/
    slot/tokens-per-s series, TTFT and inter-token histograms, and
    kernel-route counters, and/or ``spans=`` (an
    :class:`~..obs.SpanRecorder`) for per-tick admit/decode/retire
    spans in the merged Perfetto timeline
    (:func:`~..obs.dump_merged_chrome_trace`); ``flight=`` (an
    :class:`~..obs.FlightRecorder`) for per-tick spans in the bounded
    postmortem ring plus the ``last_tick_at`` liveness stamp a flight
    watchdog probes; ``exporter=`` (an :class:`~..obs.ObsServer`) to
    register the tick-freshness ``/healthz`` check and the span
    recorder as a ``/trace`` source. With none of them, the tick path
    does no observability work at all.
    """

    def __init__(self, params, cfg: TransformerConfig, *, slots: int = 8,
                 n_inner: int = 8, eos_id: int | None = None,
                 prompt_chunk: int = 256, max_prompt: int = 2048,
                 quantize_kv: bool = False, temperature: float = 0.0,
                 top_k: int | None = None, registry=None, spans=None,
                 flight=None, exporter=None):
        W = _check_ring_cfg(cfg)
        _check_sampling_params(temperature, top_k)
        if cfg.n_experts:
            raise ValueError(
                "serving scheduler covers dense-FFN configs (MoE: see "
                "make_serving_scan's error note)"
            )
        if slots < 1 or n_inner < 1:
            raise ValueError("slots and n_inner must be >= 1")
        if prompt_chunk > max_prompt:
            raise ValueError("prompt_chunk must be <= max_prompt")
        self.params = params
        self.cfg = cfg
        self.S = int(slots)
        self.W = W
        self.n_inner = int(n_inner)
        self.eos_id = eos_id
        self.C = int(prompt_chunk)
        self.Lmax = int(max_prompt)
        self.quantize_kv = bool(quantize_kv)
        self._queue: deque[Request] = deque()
        self._slot_req: list[Request | None] = [None] * self.S
        self._admitting: dict[int, _Admitting] = {}  # slot -> state
        self.tick_count = 0
        # device-resident row state + batched ring cache arena
        self.temperature = float(temperature)
        self.top_k = top_k
        self._tok = jnp.zeros((self.S,), jnp.int32)
        self._pos = jnp.zeros((self.S,), jnp.int32)
        self._done = jnp.ones((self.S,), bool)  # idle rows stay done
        self._keys = jax.random.split(jax.random.key(0), self.S)
        self._caches = _fresh_cache(cfg, self.S, W, self.quantize_kv)
        # int8 Pallas kernel routing, resolved at construction against
        # THIS scheduler's slot count (decode.py's auto gate: the tick
        # batches all S slots into one kernel call per layer, which is
        # what amortizes the scan boundary cost the B=1 path cannot)
        self.use_kernel = (
            _kernel_possible(cfg, self.quantize_kv)
            and _route_kernel(_UNSET, self.S)
        )
        self._scan = _serving_scan_dense(
            cfg, self.n_inner, eos_id, self.temperature, top_k,
            self.use_kernel,
        )
        self._extend = _extend_chunk_dense(cfg, self.C, self.Lmax)
        self._finish = _finish_admit_dense(
            cfg, self.Lmax, self.temperature, top_k
        )
        self._place = _place_dense(cfg)
        # instruments resolved once here; None = dark (no tick cost)
        self._obs = (
            _ServingObs(self, registry, spans)
            if registry is not None or spans is not None
            else None
        )
        # flight recorder (obs/flight.py, opt-in): per-tick spans land
        # in the bounded postmortem ring; dark schedulers never stamp
        self._flight = flight
        # perf_counter of the latest completed tick — the liveness
        # signal for /healthz tick-freshness checks and flight
        # watchdogs; stays None on a fully dark scheduler (the dark
        # tick reads no clocks, pinned by tests/test_obs.py). An
        # exporter-ONLY scheduler must stamp too — its registered
        # health check reads this, and a never-set stamp would report
        # an actively-ticking scheduler as stuck forever.
        self.last_tick_at: float | None = None
        self._stamp_ticks = (
            self._obs is not None or flight is not None
            or exporter is not None
        )
        if exporter is not None:
            # register the tick-freshness health check (+ the span
            # recorder as a /trace source) on the ObsServer
            exporter.register_scheduler(self)

    # -- public API -----------------------------------------------------

    def enable_tick_stamping(self) -> None:
        """Turn on the per-tick ``last_tick_at`` liveness stamp (one
        ``perf_counter`` read per tick). Construction with any of
        ``registry=``/``spans=``/``flight=``/``exporter=`` enables it
        already; :meth:`ObsServer.register_scheduler` calls this so a
        scheduler registered AFTER dark construction becomes probeable
        — its tick-freshness health check reads the stamp."""
        self._stamp_ticks = True

    def submit(self, prompt, max_new: int, key=None) -> Request:
        """Queue a request; returns the live :class:`Request` whose
        ``tokens``/``finished`` the caller watches. Admission happens
        inside subsequent ticks — requests may arrive while others are
        mid-decode (the "straggling request" case). ``key``: the
        request's PRNG key when the scheduler samples
        (``temperature > 0``); defaults to a request-id-derived key.
        A sampled stream equals ``generate_ring_dense(..., key=key)``
        for the same key (tests pin it)."""
        if key is not None and self.temperature == 0.0:
            raise ValueError(
                "submit(key=...) on a greedy scheduler: the key would "
                "be silently unused — construct the scheduler with "
                "temperature > 0 (generate_* raises the same way)"
            )
        req = Request(prompt, max_new, key=key)
        if req.prompt.size > self.Lmax:
            raise ValueError(
                f"prompt of {req.prompt.size} tokens exceeds max_prompt "
                f"{self.Lmax}; raise max_prompt (one-time recompile)"
            )
        obs = self._obs
        if obs is not None:
            req._t_submit = time.perf_counter()
        self._queue.append(req)
        if obs is not None and obs._r:
            obs.m_queue.set(len(self._queue))
        return req

    @property
    def active(self) -> int:
        """Slots currently decoding or admitting."""
        return sum(r is not None for r in self._slot_req)

    @property
    def pending(self) -> int:
        return len(self._queue)

    def _decode_scan_fetch(self) -> np.ndarray:
        """Run the jitted decode tick and fence the tokens to host."""
        (self._tok, self._pos, self._done, self._caches,
         toks) = self._scan(self.params, self._tok, self._pos,
                            self._done, self._caches, self._keys)
        return np.asarray(toks)  # (S, n_inner) one fetch per tick

    def step(self) -> list[Request]:
        """One scheduler tick; returns the requests retired in it
        (including any that retire at admission — max_new == 1 or a
        first-token EOS). When instrumented (``registry=``/``spans=``)
        the tick records admit/decode/retire spans and the queue/slot/
        token series; dark, the only additions to the hot path are
        ``obs is not None`` checks."""
        obs = self._obs
        flight = self._flight
        lit = self._stamp_ticks  # obs, flight, OR exporter attached
        t0 = time.perf_counter() if lit else 0.0
        self.tick_count += 1
        retired: list[Request] = []
        self._advance_admissions(retired)
        self._admit_from_queue(retired)
        t1 = time.perf_counter() if obs is not None else 0.0
        t2 = None
        decoding = [
            s for s, r in enumerate(self._slot_req)
            if r is not None and s not in self._admitting
        ]
        if decoding:
            if obs is None:
                host = self._decode_scan_fetch()
            else:
                # device-side span: visible inside jax.profiler traces
                # on real chips, a no-op wherever the profiler is not
                with obs.annotate("serving.decode_scan"):
                    host = self._decode_scan_fetch()
                t2 = time.perf_counter()
            for s in decoding:
                req = self._slot_req[s]
                n_before = len(req.tokens) if obs is not None else 0
                req.tokens.extend(int(t) for t in host[s])
                due = self._retire_if_due(req)
                if obs is not None:
                    # count AFTER the retirement trim: the EOS-clamped
                    # tail the host strips was never delivered to
                    # anyone, and a tokens/s series inflated by it
                    # would overstate throughput by up to n_inner-1
                    # per retiring request
                    obs.tokens_emitted(
                        req, len(req.tokens) - n_before, t2
                    )
                if due:
                    self._free_slot(s)
                    retired.append(req)
        if obs is not None:
            obs.tick_done(self, retired, t0, t1, t2)
        if lit:
            now = time.perf_counter()
            self.last_tick_at = now
            if flight is not None:
                flight.span(
                    f"tick {self.tick_count}", t0, now - t0,
                    src="scheduler", track="scheduler",
                    queue=self.pending, active=self.active,
                    retired=len(retired),
                )
                flight.counter(
                    "serving_ticks_total", self.tick_count, t=now
                )
        return retired

    def run(self, max_ticks: int = 10_000) -> None:
        """Tick until every queued and in-flight request retires."""
        for _ in range(max_ticks):
            if not self._queue and self.active == 0:
                return
            self.step()
        raise RuntimeError(
            f"not drained after {max_ticks} ticks: {self.pending} "
            f"queued, {self.active} active"
        )

    # -- admission ------------------------------------------------------

    def _admit_from_queue(self, retired: list[Request]) -> None:
        free = [s for s, r in enumerate(self._slot_req) if r is None]
        while self._queue and free:
            s = free.pop(0)
            req = self._queue.popleft()
            Tp = req.prompt.size
            n_chunks = -(-Tp // self.C)
            padded = np.zeros((1, n_chunks * self.C), np.int32)
            padded[0, :Tp] = req.prompt
            cache = _fresh_cache(self.cfg, 1, self.Lmax,
                                 self.quantize_kv)
            self._slot_req[s] = req
            self._admitting[s] = _Admitting(
                req, cache, jnp.asarray(padded), n_chunks
            )
            req.admitted_tick = self.tick_count
            # first chunk runs this very tick (short prompts admit in
            # one tick and decode from the next)
            self._advance_admission(s, retired)

    def _advance_admissions(self, retired: list[Request]) -> None:
        for s in list(self._admitting):
            self._advance_admission(s, retired)

    def _advance_admission(self, s: int,
                           retired: list[Request]) -> None:
        st = self._admitting[s]
        i = st.next_chunk
        chunk = jax.lax.dynamic_slice_in_dim(
            st.padded, i * self.C, self.C, axis=1
        )
        st.last_logits, st.cache = self._extend(
            self.params, chunk, st.cache, jnp.int32(i * self.C)
        )
        st.next_chunk += 1
        if self._obs is not None:
            self._obs.prefill_chunk()
        if st.next_chunk < st.n_chunks:
            return
        Tp = st.req.prompt.size
        rkey = (st.req.key if st.req.key is not None
                else jax.random.key(st.req.id + 1))
        tok0, ring = self._finish(
            st.cache, st.last_logits, jnp.int32(Tp),
            jnp.int32((st.n_chunks - 1) * self.C), rkey,
        )
        (self._caches, self._tok, self._pos, self._done,
         self._keys) = self._place(
            self._caches, ring, self._tok, self._pos, self._done,
            self._keys, jnp.int32(s), tok0, jnp.int32(Tp), rkey,
        )
        st.req.tokens.append(int(tok0))
        if self._obs is not None:
            self._obs.first_token(st.req, time.perf_counter())
        del self._admitting[s]
        if self._retire_if_due(st.req):  # max_new == 1 or prompt EOS
            self._free_slot(s)
            retired.append(st.req)

    # -- retirement -----------------------------------------------------

    def _retire_if_due(self, req: Request) -> bool:
        cut = None
        if self.eos_id is not None and req._eos_at is None:
            # scan only this tick's new tokens (a long-lived request
            # must not pay a full-history scan per tick)
            try:
                req._eos_at = req.tokens.index(
                    self.eos_id, req._scanned
                )
            except ValueError:
                pass
            req._scanned = len(req.tokens)
        if req._eos_at is not None:
            cut = req._eos_at + 1
            if cut <= req.max_new:
                req.reason = "eos"
            else:
                cut = None
        if cut is None and len(req.tokens) >= req.max_new:
            cut = req.max_new
            req.reason = "length"
        if cut is None:
            return False
        del req.tokens[cut:]
        req.finished = True
        req.retired_tick = self.tick_count
        return True

    def _free_slot(self, s: int) -> None:
        self._slot_req[s] = None
        # the row keeps decoding garbage until reused — done=True makes
        # it emit EOS-clamped tokens nobody reads; admission resets it
        self._done = self._done.at[s].set(True)
