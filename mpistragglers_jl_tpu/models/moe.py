"""Mixture-of-experts FFN with expert parallelism over an ``"ep"`` axis.

The reference has no model code and exactly one parallelism strategy
(coordinator/worker data-parallel map — SURVEY §2 "Parallelism
strategies"); expert parallelism is a north-star capability this
framework adds so the flagship transformer exercises every axis of a
modern TPU mesh (dp, sp, tp, ep) in one program.

Design (TPU-first, GShard/Switch lineage):

* **Top-1 routing with static capacity.** Every shape is static: each
  token picks its argmax expert, takes a slot among that expert's
  ``capacity`` slots (computed by a cumsum over the one-hot dispatch —
  no sort, no dynamic shapes), and tokens beyond capacity are dropped
  (they ride the residual connection, the standard Switch behavior).
  The router gradient flows through the gate probability that scales
  the combined expert output.
* **Dispatch/combine as einsums.** The (tokens, experts, capacity)
  one-hot dispatch tensor turns routing into two MXU-friendly einsums
  (gather-free), exactly the Mesh-TensorFlow formulation.
* **Expert parallelism = all_to_all over ``"ep"``.** Experts are
  sharded over the ``ep`` mesh axis and the *batch* is sharded over
  ``(dp, ep)`` — every ep member holds distinct tokens, so the tiled
  ``all_to_all`` exchanges "my tokens for your experts" in one ICI
  collective each way, the expert FFN runs on local experts only, and
  a second all_to_all restores token ownership.
* **tp composes.** Each expert's hidden dim is additionally sharded
  over ``tp`` (Megatron split); the caller psums the down-projection
  over ``tp`` exactly like the dense MLP path.

The dense path (:func:`moe_ffn_dense`) runs identical routing math with
all experts resident — it is the correctness oracle for the sharded
path and the single-chip execution mode.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "init_moe_layer",
    "moe_layer_specs",
    "switch_route",
    "moe_ffn_dense",
    "moe_ffn_sharded",
]


def init_moe_layer(rng: np.random.Generator, d_model: int, d_ff: int,
                   n_experts: int, n_layers: int, dtype) -> dict:
    """Per-layer MoE params: router + stacked expert FFN weights.

    Expert weights carry a leading (n_experts,) axis — the axis the
    ``ep`` PartitionSpec shards.
    """
    E, D, F = n_experts, d_model, d_ff
    sd = lambda *s: jnp.asarray(
        rng.standard_normal(s) / np.sqrt(s[-2]), dtype
    )
    return {
        "wg": jnp.asarray(rng.standard_normal((D, E)) * 0.02, dtype),
        "we1": sd(E, D, F),
        "be1": jnp.zeros((E, F), dtype),
        # float(): np.float64 scalars promote f32 params under x64
        "we2": sd(E, F, D) / float(np.sqrt(n_layers)),
        "be2": jnp.zeros((E, D), dtype),
    }


def moe_layer_specs():
    """PartitionSpecs for :func:`init_moe_layer`: experts over ``ep``,
    the expert hidden dim over ``tp``, router replicated."""
    from jax.sharding import PartitionSpec as P

    return {
        "wg": P(),
        "we1": P("ep", None, "tp"),
        "be1": P("ep", "tp"),
        "we2": P("ep", "tp", None),
        "be2": P("ep", None),
    }


def switch_route(x2d: jax.Array, wg: jax.Array, capacity: int):
    """Top-1 routing of (T, D) tokens over E = wg.shape[1] experts.

    Returns ``(dispatch, combine, aux)``:

    * ``dispatch`` — (T, E, C) 0/1 float: token t occupies slot c of
      expert e. At most ``capacity`` tokens per expert (cumsum slot
      assignment in arrival order); overflow rows are all-zero.
    * ``combine`` — ``dispatch`` scaled by the token's gate probability;
      contracting expert outputs against it yields the MoE output (and
      routes the gradient into the router).
    * ``aux`` — Switch load-balance loss ``E * sum_e f_e * p_e`` where
      ``f_e`` is the dispatched-token fraction and ``p_e`` the mean
      router probability of expert e; 1.0 at perfect balance.
    """
    E = wg.shape[1]
    logits = x2d.astype(jnp.float32) @ wg.astype(jnp.float32)  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (T, E)
    # slot within the chosen expert, in token order; >= capacity drops
    slot = (jnp.cumsum(onehot, axis=0) - onehot) * onehot  # (T, E)
    slot = slot.sum(axis=1).astype(jnp.int32)  # (T,)
    dispatch = onehot[:, :, None] * jax.nn.one_hot(
        slot, capacity, dtype=jnp.float32
    )[:, None, :]  # (T, E, C); one_hot(slot >= C) is all-zero = dropped
    combine = dispatch * gate[:, None, None].astype(jnp.float32)
    frac = onehot.mean(axis=0)
    aux = E * jnp.sum(frac * probs.mean(axis=0))
    return dispatch, combine, aux


def _expert_ffn(xe, mp):
    """Per-expert FFN on dispatched tokens xe (E_local, C', D); weights
    carry matching local leading axis."""
    a = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", xe, mp["we1"]) + mp["be1"][:, None, :]
    )
    return jnp.einsum("ecf,efd->ecd", a, mp["we2"])


def moe_ffn_dense(x: jax.Array, mp: dict, capacity_factor: float):
    """Oracle/single-chip MoE FFN on (B, L, D); all experts resident.

    Returns ``(y, aux)``; dropped tokens contribute zeros to y (the
    caller's residual connection carries them through). ``be2`` is added
    via the combine weights so dropped tokens see no bias — the sharded
    path reproduces this exactly.
    """
    B, L, D = x.shape
    E = mp["wg"].shape[1]
    C = _capacity(B * L, E, capacity_factor)
    x2d = x.reshape(B * L, D)
    dispatch, combine, aux = switch_route(x2d, mp["wg"], C)
    xe = jnp.einsum("td,tec->ecd", x2d, dispatch.astype(x.dtype))
    ye = _expert_ffn(xe, mp) + mp["be2"][:, None, :]
    y = jnp.einsum("ecd,tec->td", ye, combine.astype(x.dtype))
    return y.reshape(B, L, D), aux


def moe_ffn_sharded(x: jax.Array, mp: dict, capacity_factor: float,
                    *, ep_axis: str = "ep", tp_axis: str = "tp"):
    """Expert-parallel MoE FFN; call inside shard_map.

    ``x`` is the (B_local, L_local, D) activation chunk (batch sharded
    over (dp, ep), sequence over sp); ``mp`` holds the ep x tp-local
    expert shards per :func:`moe_layer_specs`. Routing and capacity are
    computed over *local* tokens (GShard convention). One tiled
    all_to_all ships dispatched tokens to their expert's owner, the
    expert FFN runs on (E/ep) local experts, and the inverse all_to_all
    ships results home. The caller must ``psum`` the returned y over
    ``tp`` (matching the dense-MLP Megatron pattern); the tp-replicated
    ``be2`` is folded in *after* that psum via the returned ``ybias``.

    Returns ``(y_partial, ybias, aux)`` with
    ``y = psum(y_partial, tp) + ybias``.
    """
    ep = jax.lax.axis_size(ep_axis)
    B, L, D = x.shape
    E_local = mp["we1"].shape[0]
    E = E_local * ep
    C = _capacity(B * L, E, capacity_factor)
    x2d = x.reshape(B * L, D)
    # router: wg is replicated; logits over ALL E experts
    dispatch, combine, aux = switch_route(x2d, mp["wg"], C)
    xe = jnp.einsum("td,tec->ecd", x2d, dispatch.astype(x.dtype))
    # (E, C, D) -> ship expert-group j to ep member j; receive my
    # E_local experts' slots from every member: (E_local, ep*C, D)
    xe = jax.lax.all_to_all(
        xe, ep_axis, split_axis=0, concat_axis=1, tiled=True
    )
    ye = _expert_ffn(xe, mp)  # tp-partial over the d_ff shard
    # inverse: split the capacity axis back per source, return home
    ye = jax.lax.all_to_all(
        ye, ep_axis, split_axis=1, concat_axis=0, tiled=True
    )  # (E, C, D), tp-partial
    y = jnp.einsum("ecd,tec->td", ye, combine.astype(x.dtype))
    # be2 is replicated over tp, so it must bypass the caller's tp psum;
    # gather the full (E, D) table (E is small) and weight it per token
    # by the gate mass of its non-dropped slot, matching the dense path
    be2 = jax.lax.all_gather(mp["be2"], ep_axis, axis=0, tiled=True)
    ybias = jnp.einsum("ed,tec->td", be2, combine.astype(x.dtype))
    return y.reshape(B, L, D), ybias.reshape(B, L, D), aux


def _capacity(tokens: int, n_experts: int, capacity_factor: float) -> int:
    return max(1, int(np.ceil(tokens / n_experts * capacity_factor)))
