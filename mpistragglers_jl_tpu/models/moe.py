"""Mixture-of-experts FFN with expert parallelism over an ``"ep"`` axis.

The reference has no model code and exactly one parallelism strategy
(coordinator/worker data-parallel map — SURVEY §2 "Parallelism
strategies"); expert parallelism is a north-star capability this
framework adds so the flagship transformer exercises every axis of a
modern TPU mesh (dp, sp, tp, ep) in one program.

Design (TPU-first, GShard/Switch lineage):

* **Top-1 routing with static capacity.** Every shape is static: each
  token picks its argmax expert, takes a slot among that expert's
  ``capacity`` slots (computed by a cumsum over the one-hot dispatch —
  no sort, no dynamic shapes), and tokens beyond capacity are dropped
  (they ride the residual connection, the standard Switch behavior).
  The router gradient flows through the gate probability that scales
  the combined expert output.
* **Dispatch/combine as gather/scatter.** Routing materializes a
  static (experts, capacity) token-index table
  (:func:`switch_route_indices`); dispatch is one gather, combine one
  scatter-add — O(E*C*D) HBM traffic and no MXU work. The classic
  Mesh-TF one-hot einsum formulation (:func:`switch_route`) is kept as
  the oracle the gather form is tested equal against: its (T, E*C, D)
  dispatch matmuls are quadratic in token count and cost more than the
  expert FFNs themselves at flagship token counts (docs/PERF.md
  round 4).
* **Expert parallelism = all_to_all over ``"ep"``.** Experts are
  sharded over the ``ep`` mesh axis and the *batch* is sharded over
  ``(dp, ep)`` — every ep member holds distinct tokens, so the tiled
  ``all_to_all`` exchanges "my tokens for your experts" in one ICI
  collective each way, the expert FFN runs on local experts only, and
  a second all_to_all restores token ownership.
* **tp composes.** Each expert's hidden dim is additionally sharded
  over ``tp`` (Megatron split); the caller psums the down-projection
  over ``tp`` exactly like the dense MLP path.

The dense path (:func:`moe_ffn_dense`) runs identical routing math with
all experts resident — it is the correctness oracle for the sharded
path and the single-chip execution mode.
"""

from __future__ import annotations

import jax
from .. import _jax_compat  # noqa: F401  (installs older-JAX aliases)
import jax.numpy as jnp
import numpy as np

__all__ = [
    "init_moe_layer",
    "moe_layer_specs",
    "switch_route",
    "switch_route_indices",
    "moe_ffn_dense",
    "moe_ffn_sharded",
]


def init_moe_layer(rng: np.random.Generator, d_model: int, d_ff: int,
                   n_experts: int, n_layers: int, dtype) -> dict:
    """Per-layer MoE params: router + stacked expert FFN weights.

    Expert weights carry a leading (n_experts,) axis — the axis the
    ``ep`` PartitionSpec shards.
    """
    E, D, F = n_experts, d_model, d_ff
    sd = lambda *s: jnp.asarray(
        rng.standard_normal(s) / np.sqrt(s[-2]), dtype
    )
    return {
        # the router stays f32 at ANY model dtype: wg is only (D, E) —
        # E columns of weights, bytes that round to zero next to the
        # expert FFNs — while routing decisions (argmax over logits,
        # gate magnitudes, the load-balance loss) are exactly the
        # quantities bf16 rounding perturbs first. tests/test_moe.py
        # pins bf16-activation routing against the f32 router.
        "wg": jnp.asarray(rng.standard_normal((D, E)) * 0.02,
                          jnp.float32),
        "we1": sd(E, D, F),
        "be1": jnp.zeros((E, F), dtype),
        # float(): np.float64 scalars promote f32 params under x64
        "we2": sd(E, F, D) / float(np.sqrt(n_layers)),
        "be2": jnp.zeros((E, D), dtype),
    }


def moe_layer_specs():
    """PartitionSpecs for :func:`init_moe_layer`: experts over ``ep``,
    the expert hidden dim over ``tp``, router replicated."""
    from jax.sharding import PartitionSpec as P

    return {
        "wg": P(),
        "we1": P("ep", None, "tp"),
        "be1": P("ep", "tp"),
        "we2": P("ep", "tp", None),
        "be2": P("ep", None),
    }


def switch_route(x2d: jax.Array, wg: jax.Array, capacity: int):
    """Top-1 routing of (T, D) tokens over E = wg.shape[1] experts.

    Returns ``(dispatch, combine, aux)``:

    * ``dispatch`` — (T, E, C) 0/1 float: token t occupies slot c of
      expert e. At most ``capacity`` tokens per expert (cumsum slot
      assignment in arrival order); overflow rows are all-zero.
    * ``combine`` — ``dispatch`` scaled by the token's gate probability;
      contracting expert outputs against it yields the MoE output (and
      routes the gradient into the router).
    * ``aux`` — Switch load-balance loss ``E * sum_e f_e * p_e`` where
      ``f_e`` is the dispatched-token fraction and ``p_e`` the mean
      router probability of expert e; 1.0 at perfect balance.
    """
    E = wg.shape[1]
    expert, slot, gate, aux = _route(x2d, wg)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (T, E)
    dispatch = onehot[:, :, None] * jax.nn.one_hot(
        slot, capacity, dtype=jnp.float32
    )[:, None, :]  # (T, E, C); one_hot(slot >= C) is all-zero = dropped
    combine = dispatch * gate[:, None, None].astype(jnp.float32)
    return dispatch, combine, aux


def _route(x2d: jax.Array, wg: jax.Array):
    """The router core shared by both routing forms: top-1 expert,
    cumsum slot (in token order), gate probability, Switch aux loss.
    Returns ``(expert (T,), slot (T,), gate (T,) f32, aux)``."""
    # f32 ACCUMULATION without materializing an f32 copy of the whole
    # (T, D) activation (the astype form wrote+read 2x64 MB per layer
    # for a 4-column matmul — the single largest routing cost measured
    # in benchmarks/moe_route_attrib.py). The router WEIGHT is not
    # downcast to the activation dtype: wg stays f32 (it is only
    # (D, E)) and the mixed-precision dot accumulates in f32 via
    # preferred_element_type — bf16 rounding touches the activations
    # once (they already are bf16), never the router's parameters.
    logits = jnp.einsum(
        "td,de->te", x2d, wg,
        preferred_element_type=jnp.float32,
    )  # (T, E) f32
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(expert, wg.shape[1], dtype=jnp.float32)
    # slot within the chosen expert, in token order; >= capacity drops
    slot = (jnp.cumsum(onehot, axis=0) - onehot) * onehot  # (T, E)
    slot = slot.sum(axis=1).astype(jnp.int32)  # (T,)
    frac = onehot.mean(axis=0)
    aux = wg.shape[1] * jnp.sum(frac * probs.mean(axis=0))
    return expert, slot, gate, aux


def switch_route_indices(x2d: jax.Array, wg: jax.Array, capacity: int):
    """Top-1 routing as static-shape INDEX TABLES (the gather/scatter
    form of :func:`switch_route`).

    The one-hot ``dispatch``/``combine`` tensors of the Mesh-TF
    formulation turn routing into (T, E*C, D) matmuls — quadratic in
    token count (T=16k tokens at the flagship rung shape is ~0.7
    TFLOP per layer of pure dispatch, more than the expert FFNs
    themselves). This form replaces them with a (E, C) token-index
    table: dispatch is a gather, combine is a scatter-add — O(E*C*D)
    HBM traffic, zero MXU work, identical semantics (same cumsum slot
    assignment, same capacity drops; measured-equal to the one-hot
    path in tests/test_moe.py).

    Returns ``(table, expert, gate, aux)``: ``table[e, c]`` is the
    token index occupying slot c of expert e, or ``T`` (a sentinel one
    past the last token) for empty slots; ``expert`` (T,) each token's
    chosen expert; ``gate`` (T,) f32 router probabilities of the chosen
    expert; ``aux`` the Switch load-balance loss.
    """
    table, expert, _, gate, aux = _route_tables(x2d, wg, capacity)
    return table, expert, gate, aux


def _route_tables(x2d: jax.Array, wg: jax.Array, capacity: int):
    """:func:`switch_route_indices` plus the per-token ``slot`` — the
    inverse seating map the gather-form backward passes need.

    The (E, C) table is built by a STABLE SORT of token indices by
    expert, not a scatter: a (T,)-element scatter serializes on the
    TPU and measured as a chip-rate-invariant ~ms-scale floor in the
    MoE step (the step barely moved when the chip's minute-rate did —
    r5). Sort keeps token order within each expert group, so sorted
    position == the cumsum slot and the two constructions agree
    exactly (pinned against the one-hot oracle in tests)."""
    T = x2d.shape[0]
    E = wg.shape[1]
    expert, slot, gate, aux = _route(x2d, wg)
    # tokens grouped by expert, token order preserved within a group
    _, sorted_tok = jax.lax.sort(
        (expert, jnp.arange(T, dtype=jnp.int32)), num_keys=1,
        is_stable=True,
    )
    counts = jnp.sum(
        jax.nn.one_hot(expert, E, dtype=jnp.int32), axis=0
    )  # (E,)
    start = jnp.cumsum(counts) - counts  # exclusive prefix
    c_idx = jnp.arange(capacity, dtype=jnp.int32)[None, :]  # (1, C)
    flat = start[:, None] + c_idx  # (E, C) indices into sorted_tok
    seated = jnp.take(
        sorted_tok, jnp.minimum(flat, T - 1), axis=0
    )
    valid = c_idx < counts[:, None]
    table = jnp.where(valid, seated, T)
    return table, expert, slot, gate, aux


# Dispatch and combine are the SAME bijection between kept tokens and
# their (expert, slot) seats, applied in opposite directions — so both
# directions of both ops are GATHERS. Left to autodiff, the transpose
# of each gather is a scatter-add, and TPU scatter-adds (plus the
# sentinel row's duplicate indices) measured as the dominant routing
# cost in the r4 rung (benchmarks/moe_route_attrib.py); the custom
# VJPs below express each backward as the inverse gather instead,
# eliminating every (T-or-EC, D)-scale scatter from the layer.


def _int_zero(a):
    """float0 cotangent for an integer primal (custom_vjp contract)."""
    return np.zeros(a.shape, dtype=jax.dtypes.float0)


def _seat_gather(x2d, table):
    T = x2d.shape[0]
    safe = jnp.minimum(table, T - 1)
    return x2d[safe] * (table < T)[..., None].astype(x2d.dtype)


def _token_gather(w_ecd, expert, slot):
    E, C, D = w_ecd.shape
    kept = (slot < C)[:, None].astype(w_ecd.dtype)
    idx = expert * C + jnp.minimum(slot, C - 1)
    return w_ecd.reshape(E * C, D)[idx] * kept


@jax.custom_vjp
def _gather_dispatch(x2d, table, expert, slot):
    """(T, D) tokens -> (E, C, D) expert slots; empty slots are zeros.
    ``expert``/``slot`` ((T,), from :func:`_route`) are the inverse
    seating map driving the gather-form backward."""
    return _seat_gather(x2d, table)


def _gather_dispatch_fwd(x2d, table, expert, slot):
    return _seat_gather(x2d, table), (table, expert, slot)


def _gather_dispatch_bwd(res, g):
    table, expert, slot = res
    return (_token_gather(g, expert, slot), _int_zero(table),
            _int_zero(expert), _int_zero(slot))


_gather_dispatch.defvjp(_gather_dispatch_fwd, _gather_dispatch_bwd)


@jax.custom_vjp
def _combine_per_token(w_ecd, table, expert, slot):
    """(E, C, D) weighted slots -> (T, D): each token reads its own
    seat (dropped tokens read zero). Equal to the scatter-add combine
    because the seating is a bijection; both directions — like both
    directions of :func:`_gather_dispatch` — are gathers."""
    return _token_gather(w_ecd, expert, slot)


def _combine_per_token_fwd(w_ecd, table, expert, slot):
    return _token_gather(w_ecd, expert, slot), (table, expert, slot)


def _combine_per_token_bwd(res, g):
    table, expert, slot = res
    # dw[e, c] = dy[token seated at (e, c)], zero for empty seats —
    # exactly the dispatch gather applied to the cotangent
    return (_seat_gather(g, table), _int_zero(table),
            _int_zero(expert), _int_zero(slot))


_combine_per_token.defvjp(_combine_per_token_fwd, _combine_per_token_bwd)


def _scatter_combine(weighted, table, T):
    """Scatter-add oracle for :func:`_combine_per_token` (kept for the
    equivalence test; the hot paths use the gather form)."""
    E, C, D = weighted.shape
    y = jnp.zeros((T + 1, D), weighted.dtype)
    y = y.at[table.reshape(-1)].add(weighted.reshape(E * C, D))
    return y[:T]


def _expert_ffn(xe, mp):
    """Per-expert FFN on dispatched tokens xe (E_local, C', D); weights
    carry matching local leading axis."""
    a = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", xe, mp["we1"]) + mp["be1"][:, None, :]
    )
    return jnp.einsum("ecf,efd->ecd", a, mp["we2"])


def moe_ffn_dense(x: jax.Array, mp: dict, capacity_factor: float):
    """Oracle/single-chip MoE FFN on (B, L, D); all experts resident.

    Returns ``(y, aux)``; dropped tokens contribute zeros to y (the
    caller's residual connection carries them through). ``be2`` is added
    via the combine weights so dropped tokens see no bias — the sharded
    path reproduces this exactly.
    """
    B, L, D = x.shape
    E = mp["wg"].shape[1]
    T = B * L
    C = _capacity(T, E, capacity_factor)
    x2d = x.reshape(T, D)
    table, expert, slot, gate, aux = _route_tables(x2d, mp["wg"], C)
    xe = _gather_dispatch(x2d, table, expert, slot)
    ye = _expert_ffn(xe, mp) + mp["be2"][:, None, :]
    # per-token combine (gather form); the gate multiply stays outside
    # the custom-vjp op so the router gradient flows through it
    yt = _combine_per_token(ye, table, expert, slot)
    kg = jnp.where(slot < C, gate, 0.0).astype(x.dtype)  # dropped -> 0
    y = yt * kg[:, None]
    return y.reshape(B, L, D), aux


def moe_ffn_sharded(x: jax.Array, mp: dict, capacity_factor: float,
                    *, ep_axis: str = "ep", tp_axis: str = "tp"):
    """Expert-parallel MoE FFN; call inside shard_map.

    ``x`` is the (B_local, L_local, D) activation chunk (batch sharded
    over (dp, ep), sequence over sp); ``mp`` holds the ep x tp-local
    expert shards per :func:`moe_layer_specs`. Routing and capacity are
    computed over *local* tokens (GShard convention). One tiled
    all_to_all ships dispatched tokens to their expert's owner, the
    expert FFN runs on (E/ep) local experts, and the inverse all_to_all
    ships results home. The caller must ``psum`` the returned y over
    ``tp`` (matching the dense-MLP Megatron pattern); the tp-replicated
    ``be2`` is folded in *after* that psum via the returned ``ybias``.

    Returns ``(y_partial, ybias, aux)`` with
    ``y = psum(y_partial, tp) + ybias``.
    """
    ep = jax.lax.axis_size(ep_axis)
    B, L, D = x.shape
    E_local = mp["we1"].shape[0]
    E = E_local * ep
    T = B * L
    C = _capacity(T, E, capacity_factor)
    x2d = x.reshape(T, D)
    # router: wg is replicated; logits over ALL E experts. Gather-form
    # dispatch (see switch_route_indices) — the (E, C, D) slot tensor
    # the all_to_all ships is built by a gather, not a T x E*C matmul.
    table, expert, slot, gate, aux = _route_tables(x2d, mp["wg"], C)
    xe = _gather_dispatch(x2d, table, expert, slot)
    # (E, C, D) -> ship expert-group j to ep member j; receive my
    # E_local experts' slots from every member: (E_local, ep*C, D)
    xe = jax.lax.all_to_all(
        xe, ep_axis, split_axis=0, concat_axis=1, tiled=True
    )
    ye = _expert_ffn(xe, mp)  # tp-partial over the d_ff shard
    # inverse: split the capacity axis back per source, return home
    ye = jax.lax.all_to_all(
        ye, ep_axis, split_axis=1, concat_axis=0, tiled=True
    )  # (E, C, D), tp-partial
    yt = _combine_per_token(ye, table, expert, slot)  # (T, D) tp-partial
    kg = jnp.where(slot < C, gate, 0.0).astype(x.dtype)  # dropped -> 0
    y = yt * kg[:, None]
    # be2 is replicated over tp, so it must bypass the caller's tp psum.
    # It is a rank-1 per-token quantity: kept-gate[t] * be2[expert[t]]
    # (one row gather — review r4; the kept mask is just slot < C now).
    be2 = jax.lax.all_gather(mp["be2"], ep_axis, axis=0, tiled=True)
    ybias = kg[:, None] * be2[expert]
    return y.reshape(B, L, D), ybias.reshape(B, L, D), aux


def _capacity(tokens: int, n_experts: int, capacity_factor: float) -> int:
    return max(1, int(np.ceil(tokens / n_experts * capacity_factor)))
