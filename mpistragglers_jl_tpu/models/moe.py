"""Mixture-of-experts FFN with expert parallelism over an ``"ep"`` axis.

The reference has no model code and exactly one parallelism strategy
(coordinator/worker data-parallel map — SURVEY §2 "Parallelism
strategies"); expert parallelism is a north-star capability this
framework adds so the flagship transformer exercises every axis of a
modern TPU mesh (dp, sp, tp, ep) in one program.

Design (TPU-first, GShard/Switch lineage):

* **Top-1 routing with static capacity.** Every shape is static: each
  token picks its argmax expert, takes a slot among that expert's
  ``capacity`` slots (computed by a cumsum over the one-hot dispatch —
  no sort, no dynamic shapes), and tokens beyond capacity are dropped
  (they ride the residual connection, the standard Switch behavior).
  The router gradient flows through the gate probability that scales
  the combined expert output.
* **Dispatch/combine as gather/scatter.** Routing materializes a
  static (experts, capacity) token-index table
  (:func:`switch_route_indices`); dispatch is one gather, combine one
  scatter-add — O(E*C*D) HBM traffic and no MXU work. The classic
  Mesh-TF one-hot einsum formulation (:func:`switch_route`) is kept as
  the oracle the gather form is tested equal against: its (T, E*C, D)
  dispatch matmuls are quadratic in token count and cost more than the
  expert FFNs themselves at flagship token counts (docs/PERF.md
  round 4).
* **Expert parallelism = all_to_all over ``"ep"``.** Experts are
  sharded over the ``ep`` mesh axis and the *batch* is sharded over
  ``(dp, ep)`` — every ep member holds distinct tokens, so the tiled
  ``all_to_all`` exchanges "my tokens for your experts" in one ICI
  collective each way, the expert FFN runs on local experts only, and
  a second all_to_all restores token ownership.
* **tp composes.** Each expert's hidden dim is additionally sharded
  over ``tp`` (Megatron split); the caller psums the down-projection
  over ``tp`` exactly like the dense MLP path.

The dense path (:func:`moe_ffn_dense`) runs identical routing math with
all experts resident — it is the correctness oracle for the sharded
path and the single-chip execution mode.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "init_moe_layer",
    "moe_layer_specs",
    "switch_route",
    "switch_route_indices",
    "moe_ffn_dense",
    "moe_ffn_sharded",
]


def init_moe_layer(rng: np.random.Generator, d_model: int, d_ff: int,
                   n_experts: int, n_layers: int, dtype) -> dict:
    """Per-layer MoE params: router + stacked expert FFN weights.

    Expert weights carry a leading (n_experts,) axis — the axis the
    ``ep`` PartitionSpec shards.
    """
    E, D, F = n_experts, d_model, d_ff
    sd = lambda *s: jnp.asarray(
        rng.standard_normal(s) / np.sqrt(s[-2]), dtype
    )
    return {
        "wg": jnp.asarray(rng.standard_normal((D, E)) * 0.02, dtype),
        "we1": sd(E, D, F),
        "be1": jnp.zeros((E, F), dtype),
        # float(): np.float64 scalars promote f32 params under x64
        "we2": sd(E, F, D) / float(np.sqrt(n_layers)),
        "be2": jnp.zeros((E, D), dtype),
    }


def moe_layer_specs():
    """PartitionSpecs for :func:`init_moe_layer`: experts over ``ep``,
    the expert hidden dim over ``tp``, router replicated."""
    from jax.sharding import PartitionSpec as P

    return {
        "wg": P(),
        "we1": P("ep", None, "tp"),
        "be1": P("ep", "tp"),
        "we2": P("ep", "tp", None),
        "be2": P("ep", None),
    }


def switch_route(x2d: jax.Array, wg: jax.Array, capacity: int):
    """Top-1 routing of (T, D) tokens over E = wg.shape[1] experts.

    Returns ``(dispatch, combine, aux)``:

    * ``dispatch`` — (T, E, C) 0/1 float: token t occupies slot c of
      expert e. At most ``capacity`` tokens per expert (cumsum slot
      assignment in arrival order); overflow rows are all-zero.
    * ``combine`` — ``dispatch`` scaled by the token's gate probability;
      contracting expert outputs against it yields the MoE output (and
      routes the gradient into the router).
    * ``aux`` — Switch load-balance loss ``E * sum_e f_e * p_e`` where
      ``f_e`` is the dispatched-token fraction and ``p_e`` the mean
      router probability of expert e; 1.0 at perfect balance.
    """
    E = wg.shape[1]
    expert, slot, gate, aux = _route(x2d, wg)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (T, E)
    dispatch = onehot[:, :, None] * jax.nn.one_hot(
        slot, capacity, dtype=jnp.float32
    )[:, None, :]  # (T, E, C); one_hot(slot >= C) is all-zero = dropped
    combine = dispatch * gate[:, None, None].astype(jnp.float32)
    return dispatch, combine, aux


def _route(x2d: jax.Array, wg: jax.Array):
    """The router core shared by both routing forms: top-1 expert,
    cumsum slot (in token order), gate probability, Switch aux loss.
    Returns ``(expert (T,), slot (T,), gate (T,) f32, aux)``."""
    logits = x2d.astype(jnp.float32) @ wg.astype(jnp.float32)  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(expert, wg.shape[1], dtype=jnp.float32)
    # slot within the chosen expert, in token order; >= capacity drops
    slot = (jnp.cumsum(onehot, axis=0) - onehot) * onehot  # (T, E)
    slot = slot.sum(axis=1).astype(jnp.int32)  # (T,)
    frac = onehot.mean(axis=0)
    aux = wg.shape[1] * jnp.sum(frac * probs.mean(axis=0))
    return expert, slot, gate, aux


def switch_route_indices(x2d: jax.Array, wg: jax.Array, capacity: int):
    """Top-1 routing as static-shape INDEX TABLES (the gather/scatter
    form of :func:`switch_route`).

    The one-hot ``dispatch``/``combine`` tensors of the Mesh-TF
    formulation turn routing into (T, E*C, D) matmuls — quadratic in
    token count (T=16k tokens at the flagship rung shape is ~0.7
    TFLOP per layer of pure dispatch, more than the expert FFNs
    themselves). This form replaces them with a (E, C) token-index
    table: dispatch is a gather, combine is a scatter-add — O(E*C*D)
    HBM traffic, zero MXU work, identical semantics (same cumsum slot
    assignment, same capacity drops; measured-equal to the one-hot
    path in tests/test_moe.py).

    Returns ``(table, expert, gate, aux)``: ``table[e, c]`` is the
    token index occupying slot c of expert e, or ``T`` (a sentinel one
    past the last token) for empty slots; ``expert`` (T,) each token's
    chosen expert; ``gate`` (T,) f32 router probabilities of the chosen
    expert; ``aux`` the Switch load-balance loss.
    """
    T = x2d.shape[0]
    E = wg.shape[1]
    expert, slot, gate, aux = _route(x2d, wg)
    # mode="drop": tokens whose slot >= capacity never enter the table
    table = jnp.full((E, capacity), T, jnp.int32).at[expert, slot].set(
        jnp.arange(T, dtype=jnp.int32), mode="drop"
    )
    return table, expert, gate, aux


def _gather_dispatch(x2d, table):
    """(T, D) tokens -> (E, C, D) expert slots; empty slots are zeros
    (the sentinel row T gathers the zero pad)."""
    x_pad = jnp.concatenate(
        [x2d, jnp.zeros((1, x2d.shape[1]), x2d.dtype)], axis=0
    )
    return x_pad[table]


def _scatter_combine(weighted, table, T):
    """(E, C, D) weighted expert outputs -> (T, D) by scatter-add at
    the table's token indices; empty slots land on the discarded
    sentinel row, dropped tokens receive zero (the caller's residual
    carries them)."""
    E, C, D = weighted.shape
    y = jnp.zeros((T + 1, D), weighted.dtype)
    y = y.at[table.reshape(-1)].add(weighted.reshape(E * C, D))
    return y[:T]


def _expert_ffn(xe, mp):
    """Per-expert FFN on dispatched tokens xe (E_local, C', D); weights
    carry matching local leading axis."""
    a = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", xe, mp["we1"]) + mp["be1"][:, None, :]
    )
    return jnp.einsum("ecf,efd->ecd", a, mp["we2"])


def moe_ffn_dense(x: jax.Array, mp: dict, capacity_factor: float):
    """Oracle/single-chip MoE FFN on (B, L, D); all experts resident.

    Returns ``(y, aux)``; dropped tokens contribute zeros to y (the
    caller's residual connection carries them through). ``be2`` is added
    via the combine weights so dropped tokens see no bias — the sharded
    path reproduces this exactly.
    """
    B, L, D = x.shape
    E = mp["wg"].shape[1]
    T = B * L
    C = _capacity(T, E, capacity_factor)
    x2d = x.reshape(T, D)
    table, _, gate, aux = switch_route_indices(x2d, mp["wg"], C)
    xe = _gather_dispatch(x2d, table)
    ye = _expert_ffn(xe, mp) + mp["be2"][:, None, :]
    gate_pad = jnp.concatenate([gate, jnp.zeros((1,), gate.dtype)])
    g = gate_pad[table].astype(x.dtype)  # (E, C); empty slots 0
    y = _scatter_combine(ye * g[..., None], table, T)
    return y.reshape(B, L, D), aux


def moe_ffn_sharded(x: jax.Array, mp: dict, capacity_factor: float,
                    *, ep_axis: str = "ep", tp_axis: str = "tp"):
    """Expert-parallel MoE FFN; call inside shard_map.

    ``x`` is the (B_local, L_local, D) activation chunk (batch sharded
    over (dp, ep), sequence over sp); ``mp`` holds the ep x tp-local
    expert shards per :func:`moe_layer_specs`. Routing and capacity are
    computed over *local* tokens (GShard convention). One tiled
    all_to_all ships dispatched tokens to their expert's owner, the
    expert FFN runs on (E/ep) local experts, and the inverse all_to_all
    ships results home. The caller must ``psum`` the returned y over
    ``tp`` (matching the dense-MLP Megatron pattern); the tp-replicated
    ``be2`` is folded in *after* that psum via the returned ``ybias``.

    Returns ``(y_partial, ybias, aux)`` with
    ``y = psum(y_partial, tp) + ybias``.
    """
    ep = jax.lax.axis_size(ep_axis)
    B, L, D = x.shape
    E_local = mp["we1"].shape[0]
    E = E_local * ep
    T = B * L
    C = _capacity(T, E, capacity_factor)
    x2d = x.reshape(T, D)
    # router: wg is replicated; logits over ALL E experts. Gather-form
    # dispatch (see switch_route_indices) — the (E, C, D) slot tensor
    # the all_to_all ships is built by a gather, not a T x E*C matmul.
    table, expert, gate, aux = switch_route_indices(x2d, mp["wg"], C)
    xe = _gather_dispatch(x2d, table)
    # (E, C, D) -> ship expert-group j to ep member j; receive my
    # E_local experts' slots from every member: (E_local, ep*C, D)
    xe = jax.lax.all_to_all(
        xe, ep_axis, split_axis=0, concat_axis=1, tiled=True
    )
    ye = _expert_ffn(xe, mp)  # tp-partial over the d_ff shard
    # inverse: split the capacity axis back per source, return home
    ye = jax.lax.all_to_all(
        ye, ep_axis, split_axis=1, concat_axis=0, tiled=True
    )  # (E, C, D), tp-partial
    gate_pad = jnp.concatenate([gate, jnp.zeros((1,), gate.dtype)])
    g = gate_pad[table].astype(x.dtype)  # (E, C); empty slots 0
    y = _scatter_combine(ye * g[..., None], table, T)
    # be2 is replicated over tp, so it must bypass the caller's tp psum.
    # It is a rank-1 per-token quantity: kept-gate[t] * be2[expert[t]] —
    # O(T*D) (one small scatter for the kept mask + one row gather),
    # NOT an (E, C, D) broadcast + second full scatter (review r4).
    be2 = jax.lax.all_gather(mp["be2"], ep_axis, axis=0, tiled=True)
    kept = jnp.zeros((T + 1,), bool).at[table.reshape(-1)].set(True)[:T]
    kg = jnp.where(kept, gate, 0.0).astype(x.dtype)  # (T,)
    ybias = kg[:, None] * be2[expert]
    return y.reshape(B, L, D), ybias.reshape(B, L, D), aux


def _capacity(tokens: int, n_experts: int, capacity_factor: float) -> int:
    return max(1, int(np.ceil(tokens / n_experts * capacity_factor)))
