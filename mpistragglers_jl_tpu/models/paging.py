"""Host-side page-pool allocator for the paged serving KV cache.

The serving scheduler's slot-ring cache gives every slot a fixed
``(W, kv_heads, head_dim)`` arena regardless of request length: a
12-token question strands the same HBM as a window-filling novel, and
N users sharing one system prompt each pay full prefill AND full
residency. The paged layout (ROADMAP item 2; the same never-materialize
discipline as memory-efficient array redistribution, arXiv 2112.01075)
splits the arena into fixed-size pages of ``PAGE_TOKENS`` ring slots
and lets requests hold only the pages they can ever touch:

* **Free-list allocation.** Pages are interchangeable fixed-size
  blocks, so allocation is a stack pop and "defragmentation" is a
  non-problem — there is no external fragmentation to compact, which
  is the reason the pool has no defrag pass.
* **Refcounts + copy-on-write.** A page may back several slots at
  once (a shared prompt prefix). Writers never mutate a shared page:
  the scheduler's pre-tick pass copies any page a slot is about to
  write while ``refcount > 1`` (one device-side page copy), so a
  reader's bytes are immutable for as long as it holds its reference.
* **Prefix hash table.** Admission hashes the prompt's page-aligned
  prefix with a CHAINED digest (page j's key covers ``prompt[:(j+1) *
  PAGE_TOKENS]`` — K/V at position p depend on every token <= p, so
  the chain is the exact content determinant) and shares already-
  resident pages by bumping refcounts, skipping their prefill
  entirely. Registration is first-wins; a page leaves the table when
  it is freed or when its (sole) owner is about to overwrite it.

This module is deliberately jax-free (numpy + hashlib): the pool is
pure host bookkeeping, and the device-side page arrays, gathers, and
copies live in :mod:`.serving`. ``NULL_PAGE`` (page 0) is reserved:
page-table entries that no valid ring slot can reach point at it, so
stray writes from retired-but-still-ticking rows land in bytes nothing
ever reads unmasked.
"""

from __future__ import annotations

import hashlib

import numpy as np

__all__ = [
    "NULL_PAGE",
    "PagePool",
    "PagePoolExhausted",
    "prefix_page_digests",
]

NULL_PAGE = 0


class PagePoolExhausted(RuntimeError):
    """No free page satisfies the allocation. Admission treats this as
    "wait for retirements"; a mid-decode raise means the admission-time
    budget accounting is wrong (a bug, not an operating condition)."""


def prefix_page_digests(prompt, page_tokens: int,
                        max_pages: int | None = None) -> list[bytes]:
    """Chained page-aligned prefix digests of an int token sequence:
    ``digests[j]`` keys the content of ring page ``j`` and covers
    ``prompt[:(j+1) * page_tokens]`` (K/V at a position depend on the
    whole prefix through attention, so nothing shorter determines the
    page's bytes). Only FULLY covered pages get a digest; ``max_pages``
    caps the walk (the scheduler passes the ring's page count — pages
    past the window hold wrapped content and are never shareable)."""
    toks = np.ascontiguousarray(np.asarray(prompt, np.int32).reshape(-1))
    n = toks.size // int(page_tokens)
    if max_pages is not None:
        n = min(n, int(max_pages))
    out: list[bytes] = []
    h = hashlib.sha256()
    for j in range(n):
        h.update(toks[j * page_tokens:(j + 1) * page_tokens].tobytes())
        out.append(h.digest())
    return out


class PagePool:
    """Free-list page allocator with refcounts and a prefix-share hash
    table. Pure host state — single-threaded by design (it lives
    inside the scheduler's tick loop, like the rest of the host-side
    bookkeeping).

    Reservation: shared pages are only ever WRITTEN by a request that
    wraps its ring (decode writes land past the prompt until position
    W), and each such write needs one COW copy. Every :meth:`share`
    that can end in a COW — the sharer wraps, or the page's owner does
    (the page is ``volatile``) — therefore attaches one reserved page
    to the shared page. :meth:`can_alloc` admits only against ``free -
    reserved`` and :meth:`cow_alloc` consumes the page's attached
    reservation, which is what makes :class:`PagePoolExhausted`
    unreachable mid-decode regardless of WHICH holder writes first.
    Reservations a retirement strands (the sharer never wrapped)
    release automatically: a page can never carry more reservations
    than ``refcount - 1`` future COWs.
    """

    def __init__(self, n_pages: int, page_tokens: int):
        if n_pages < 2:
            raise ValueError(
                f"n_pages must be >= 2 (page 0 is the reserved null "
                f"page), got {n_pages}"
            )
        if page_tokens < 1:
            raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
        self.n_pages = int(n_pages)
        self.page_tokens = int(page_tokens)
        # LIFO free list: recently freed pages are re-used first (their
        # bytes are most likely still resident in whatever cache level)
        self._free: list[int] = list(range(self.n_pages - 1, 0, -1))
        self._ref = np.zeros(self.n_pages, np.int64)
        self._ref[NULL_PAGE] = 1  # permanently held, never allocatable
        self._digest_to_page: dict[bytes, int] = {}
        self._page_digest: dict[int, bytes] = {}
        # per-page count of CURRENT holders whose request wraps its
        # ring (and will therefore overwrite the page): sharing a page
        # with any wrapper needs a COW reservation. A count, not a
        # sticky flag — when the last wrapping holder retires (or COWs
        # away), later sharers stop paying reservations the page can
        # no longer consume (review r11: a sticky flag collapsed the
        # shared-capacity win once the registering owner retired).
        self._wrappers: dict[int, int] = {}
        # per-page attached COW reservations + their total
        self._page_reserved: dict[int, int] = {}
        self._reserved = 0
        # lifetime counters, exported by the scheduler's instruments as
        # serving_prefix_share_hits_total / serving_cow_copies_total
        self.share_hits = 0
        self.cow_copies = 0
        # fleet-cache hooks (cache/ package, opt-in): called with the
        # digest whenever a prefix page enters or leaves the share
        # table, so a fleet-level directory can mirror THIS pool's
        # registrations without polling. None = dark (no per-call cost
        # beyond the `is not None` check); the pool itself never knows
        # what is on the other end.
        self.register_hook = None
        self.unregister_hook = None

    # -- capacity -------------------------------------------------------

    @property
    def free(self) -> int:
        """Pages on the free list (null page excluded)."""
        return len(self._free)

    @property
    def used(self) -> int:
        """Allocated pages (null page excluded)."""
        return self.n_pages - 1 - len(self._free)

    @property
    def reserved(self) -> int:
        """Pages promised to admitted requests for future COW copies."""
        return self._reserved

    def can_alloc(self, n: int, *, reserve: int = 0) -> bool:
        """Would ``n`` allocations plus ``reserve`` new reservations
        fit without eating into existing reservations?"""
        return n + reserve + self._reserved <= len(self._free)

    # -- alloc / refcount ----------------------------------------------

    def alloc(self) -> int:
        """Pop a free page (refcount 1). Never dips into reserved
        pages — those belong to admitted requests' future COWs."""
        if self._reserved >= len(self._free):
            raise PagePoolExhausted(
                f"no unreserved free pages ({len(self._free)} free, "
                f"{self._reserved} reserved, {self.used} used of "
                f"{self.n_pages - 1})"
            )
        pid = self._free.pop()
        self._ref[pid] = 1
        return pid

    def incref(self, pid: int) -> None:
        if self._ref[pid] < 1 or pid == NULL_PAGE:
            raise ValueError(f"incref of unallocated page {pid}")
        self._ref[pid] += 1

    def decref(self, pid: int, *, wrapper: bool = False) -> bool:
        """Drop one reference; returns True when the page was freed
        (and unregistered from the prefix table). ``wrapper=True``
        means the LEAVING holder's request wraps its ring — the page's
        wrapper count drops with it, so sharers stop reserving against
        a writer that no longer exists. Reservations the drop strands
        — a page can carry at most ``refcount - 1`` future COWs —
        release automatically."""
        if pid == NULL_PAGE or self._ref[pid] < 1:
            raise ValueError(f"decref of unallocated page {pid}")
        self._ref[pid] -= 1
        if wrapper:
            n = self._wrappers.get(pid, 0)
            if n > 1:
                self._wrappers[pid] = n - 1
            else:
                self._wrappers.pop(pid, None)
        if self._ref[pid] > 0:
            self._clamp_reservation(pid)
            return False
        self._release_reservation(pid)
        self._wrappers.pop(pid, None)
        d = self._page_digest.pop(pid, None)
        if d is not None:
            self._digest_to_page.pop(d, None)
            if self.unregister_hook is not None:
                self.unregister_hook(d)
        self._free.append(pid)
        return True

    def refcount(self, pid: int) -> int:
        return int(self._ref[pid])

    def _clamp_reservation(self, pid: int) -> None:
        cap = int(self._ref[pid]) - 1
        have = self._page_reserved.get(pid, 0)
        if have > cap:
            self._reserved -= have - cap
            if cap:
                self._page_reserved[pid] = cap
            else:
                self._page_reserved.pop(pid, None)

    def _release_reservation(self, pid: int) -> None:
        self._reserved -= self._page_reserved.pop(pid, 0)

    # -- prefix sharing + copy-on-write ---------------------------------

    def lookup(self, digest: bytes) -> int | None:
        """Resident page holding this prefix digest, or None."""
        return self._digest_to_page.get(digest)

    def registered(self, pid: int) -> bool:
        """Is ``pid`` published in the prefix table? The validity
        witness the QoS cold-page cache keys on: registration drops
        the moment a page's bytes stop matching its digest
        (:meth:`note_write`, COW retarget, free), so a registered
        sole-held page is safe to keep resident for future sharers."""
        return pid in self._page_digest

    def digest_of(self, pid: int) -> bytes | None:
        """The prefix digest ``pid`` is registered under, or None. The
        spill path reads this BEFORE the freeing decref — a registered
        page's bytes still match its digest, which is what makes the
        page's content portable to the host-DRAM tier."""
        return self._page_digest.get(pid)

    def is_volatile(self, pid: int) -> bool:
        """Will a CURRENT holder eventually overwrite this page (some
        holder's request wraps its ring)? Sharing a volatile page
        always needs a COW reservation, however short the sharer."""
        return self._wrappers.get(pid, 0) > 0

    def share_needs_reserve(self, pid: int, sharer_wraps: bool) -> bool:
        """Does sharing ``pid`` require reserving a COW page? Yes when
        any party can ever write it: the sharer wraps, or a current
        holder does."""
        return sharer_wraps or self.is_volatile(pid)

    def share(self, pid: int, *, reserve: bool,
              wrapper: bool = False) -> None:
        """Take a reference on a prefix page (the admission hit path);
        ``reserve=True`` attaches one COW reservation to the page —
        whichever holder writes it first consumes the reservation via
        :meth:`cow_alloc`, so the copy can never fail. ``wrapper=True``
        records that the SHARER's request wraps (it joins the page's
        wrapper count like a wrapping owner does at registration)."""
        self.incref(pid)
        if wrapper:
            self._wrappers[pid] = self._wrappers.get(pid, 0) + 1
        if reserve:
            if self._reserved >= len(self._free):
                # callers gate on can_alloc first; this is the
                # belt-and-braces invariant guard
                raise PagePoolExhausted(
                    "cannot attach a COW reservation: all free pages "
                    "are already reserved"
                )
            self._page_reserved[pid] = self._page_reserved.get(pid, 0) + 1
            self._reserved += 1
        self.share_hits += 1

    def cow_alloc(self, pid: int) -> int:
        """Allocate the destination page for a copy-on-write of
        ``pid``, consuming the page's attached reservation when one
        exists (the caller then copies bytes, retargets its table
        entry, and decrefs ``pid``)."""
        have = self._page_reserved.get(pid, 0)
        if have:
            if have == 1:
                self._page_reserved.pop(pid)
            else:
                self._page_reserved[pid] = have - 1
            self._reserved -= 1
        elif self._reserved >= len(self._free):
            raise PagePoolExhausted(
                f"COW of page {pid} has no reservation and all free "
                "pages are reserved (admission accounting bug)"
            )
        if not self._free:
            raise PagePoolExhausted(
                f"no free pages ({self.used} used of {self.n_pages - 1})"
            )
        new = self._free.pop()
        self._ref[new] = 1
        self.cow_copies += 1
        return new

    def register(self, digest: bytes, pid: int, *,
                 volatile: bool = False) -> None:
        """Publish ``pid`` as the resident page for ``digest``.
        First-wins: an existing mapping (another slot registered the
        identical prefix first) is kept, and a page already registered
        under another digest keeps its original key. ``volatile=True``
        marks the page as eventually-overwritten by its owner (see
        :meth:`is_volatile`)."""
        if self._ref[pid] < 1:
            raise ValueError(f"register of unallocated page {pid}")
        if digest in self._digest_to_page or pid in self._page_digest:
            return
        self._digest_to_page[digest] = pid
        self._page_digest[pid] = digest
        if volatile:
            self._wrappers[pid] = self._wrappers.get(pid, 0) + 1
        if self.register_hook is not None:
            self.register_hook(digest, pid)

    def note_write(self, pid: int) -> None:
        """A sole owner is about to overwrite ``pid`` (ring wrap): its
        registered prefix digest — if any — no longer describes its
        future bytes, so drop it from the share table. Shared pages
        never reach here (the scheduler COWs them instead)."""
        d = self._page_digest.pop(pid, None)
        if d is not None:
            self._digest_to_page.pop(d, None)
            if self.unregister_hook is not None:
                self.unregister_hook(d)

    # -- invariants (tests + postmortems) -------------------------------

    def check(self) -> None:
        """Structural invariants: free + used == n_pages - 1, free
        pages have refcount 0, registered/volatile pages are live,
        per-page reservations fit ``refcount - 1`` and sum to the
        total, which never exceeds the free list."""
        if len(self._free) + self.used != self.n_pages - 1:
            raise AssertionError("free/used accounting drifted")
        if len(set(self._free)) != len(self._free):
            raise AssertionError("double-free: duplicate page on free list")
        for pid in self._free:
            if self._ref[pid] != 0:
                raise AssertionError(f"free page {pid} has refcount "
                                     f"{self._ref[pid]}")
        for d, pid in self._digest_to_page.items():
            if self._ref[pid] < 1:
                raise AssertionError(f"registered page {pid} is free")
            if self._page_digest.get(pid) != d:
                raise AssertionError("digest tables disagree")
        for pid, n in self._wrappers.items():
            if self._ref[pid] < 1:
                raise AssertionError(f"volatile page {pid} is free")
            if n < 1 or n > self._ref[pid]:
                raise AssertionError(
                    f"page {pid} counts {n} wrappers at refcount "
                    f"{self._ref[pid]}"
                )
        for pid, n in self._page_reserved.items():
            if n < 1 or n > self._ref[pid] - 1:
                raise AssertionError(
                    f"page {pid} carries {n} reservations at refcount "
                    f"{self._ref[pid]}"
                )
        if self._reserved != sum(self._page_reserved.values()):
            raise AssertionError("reservation totals drifted")
        if self._reserved > len(self._free):
            raise AssertionError("reservations exceed the free list")

    def stats(self) -> dict:
        return {
            "n_pages": self.n_pages - 1,
            "free": self.free,
            "used": self.used,
            "reserved": self._reserved,
            "registered_prefix_pages": len(self._digest_to_page),
            "share_hits": self.share_hits,
            "cow_copies": self.cow_copies,
        }
