"""Request routing over replicated serving schedulers: the traffic tier.

One :class:`~.serving.ServingScheduler` is a box; production is an
open-loop arrival stream hitting a FLEET of them (ROADMAP item 1). This
module is the admission/router layer in between: a
:class:`RequestRouter` owns N scheduler replicas, picks one per
arriving request under a pluggable policy, watches every routed
request to its first token and retirement, hedges requests whose TTFT
deadline blows (first-token-wins, loser cancelled — the serving-side
instance of the paper's return-at-the-fastest-k primitive, priced per
REQUEST instead of per epoch), and routes around a replica whose
health flips — then resumes when it recovers. No admitted request is
ever dropped: a dead replica's in-flight requests are re-routed onto
the survivors (at-least-once — a re-routed stream restarts from its
prompt; ``RoutedRequest.rerouted`` counts it).

Policies (``policy=``):

==================  ====================================================
``round_robin``     cycle over routable replicas — the baseline every
                    other policy is priced against
``least_loaded``    fewest ``pending + active`` requests (the live
                    queue-depth + active-slot gauges the ``_ServingObs``
                    exporters publish), ties to the lowest index
``prefix_affinity`` route by the paged cache's
                    :func:`~.paging.prefix_page_digests` chain: the
                    replica already holding the longest resident prefix
                    of this prompt wins (shared system prompts land
                    where their pages live, compounding the COW
                    capacity win) — LOAD-BOUNDED: affinity yields to
                    ``least_loaded`` once the affine replica is a full
                    slot batch deeper than the least loaded, so a hot
                    system prompt cannot melt one replica
``hedge_p99``       ``least_loaded`` placement plus TTFT-deadline
                    hedging: a request whose first token misses
                    ``ttft_slo`` is re-dispatched onto a second
                    replica via the :class:`~..utils.hedge.RequestHedge`
                    machinery; first token wins, the loser is
                    ``cancel()``-ed
``two_tier``        disaggregated placement (models/disagg.py): fresh
                    requests go ``least_loaded`` to the PREFILL tier
                    (replicas whose ``tier`` attribute is
                    ``"prefill"``); a stream's first token triggers a
                    KV-page migration to the DECODE tier — the
                    residency-affine, load-bounded decode replica
                    adopts the page set — unless its payload exceeds
                    ``migrate_threshold_bytes`` (it then decodes where
                    it prefilled). ``migrate_gbs`` prices the transfer
                    on the router clock (virtual seconds in sim; None
                    lands migrations in the same step, the live path
                    where the adoption itself takes the wall time)
==================  ====================================================

**Replica protocol.** Anything scheduler-shaped routes: ``submit(prompt,
max_new, key=None) -> request`` (the request exposing ``tokens`` /
``finished`` / ``admitted_tick``), ``step()``, ``cancel(request)``, and
the ``pending`` / ``active`` load gauges. :class:`~.serving.
ServingScheduler` satisfies it natively; :class:`~..sim.workload.
SimReplica` satisfies it on virtual time, which is how router policies
are priced offline (``sim/workload.py`` drives this very class over a
simulated diurnal day; ``sim/tune.py::sweep_router_policy`` recommends
a policy per (load, prefix-share) point). Optional members the router
uses when present: ``pool``/``P``/``max_pages`` (paged prefix
affinity), ``prefix_hits(prompt)`` (a replica-supplied affinity score,
the sim shortcut), ``alive`` (the default health probe),
``next_tick_at`` (virtual-time driver scheduling), ``last_tick_at``
(the ``/healthz`` freshness detail).

**Clocks.** ``clock=None`` reads the OS clock (live fleets);
``clock=VirtualClock()`` prices the same router — same code path, same
policies — in virtual time, bit-reproducibly. All TTFT/deadline math
uses whichever clock was given; nothing here sleeps.

**Multi-tenant QoS** (``qos=`` a :class:`~..qos.TenantRegistry`,
docs/API.md "Multi-tenant QoS"): ``submit`` then requires ``tenant=``
and becomes the budget door — the tenant's token bucket is charged
``prompt + max_new`` tokens, an over-budget SHEDDABLE (batch-class)
tenant gets the request back immediately with ``outcome == "shed"``
(named, counted, never routed), an over-budget interactive tenant is
paced by the replicas' deficit admission instead; and ``hedge_p99``
re-dispatches draw from the tenant's own entitlement (outstanding
hedge legs capped at the contract's ``hedges``, dues beyond it
refused and counted) so one tenant's deadline panic cannot consume
another's slack.

**Chaos hardening** (round 20, docs/API.md "Chaos plane"):
:meth:`~RequestRouter.partition` / :meth:`~RequestRouter.heal` model
a router<->replica NETWORK PARTITION as distinct from death — the
replica keeps ticking (its in-flight work progresses and burns
capacity), its results are unreachable, its requests re-route with
the stale legs abandoned uncancelled, and the heal withdraws them so
a rejoin can never double-retire a request. ``shed_depth=`` /
``shed_depth_hard=`` are the overload ceilings: past the soft
ceiling sheddable work (batch class; all classless traffic) is shed
BY NAME with ``shed_reason == "overload"``, past the hard ceiling
(default 2x soft) every class sheds (``"overload_hard"``) — shed
beats an unbounded queue, and graftcheck GC010 statically enforces
that no drop is ever bare. Correlated (same-instant, multi-replica)
kills evacuate only after the full health scan — see
:meth:`_probe_health`.

**Observability** is strictly opt-in (the package-wide GC004 contract):
``registry=`` exports ``router_requests_total{policy,replica,outcome}``,
``router_hedge_fired_total``, ``router_replica_ejections_total``, the
``router_queue_wait_seconds`` / ``router_ttft_seconds`` histograms, and
a per-replica ``router_replica_depth`` gauge; ``flight=`` stamps
instant events on hedge fires and replica ejections/restorations into
the postmortem ring; ``exporter=`` registers the aggregate ``/healthz``
check (per-replica status in the detail, 503 only when NO replica is
admittable — :meth:`~..obs.export.ObsServer.register_router`). Dark,
the hot path pays only ``is None`` checks.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Sequence

import numpy as np

from ..qos import TenantRegistry
from ..utils.hedge import RequestHedge
from .paging import prefix_page_digests

__all__ = ["RequestRouter", "RoutedRequest", "ROUTER_POLICIES"]

ROUTER_POLICIES = (
    "round_robin", "least_loaded", "prefix_affinity", "hedge_p99",
    "two_tier",
)

_NO_SCHEDULE = object()  # replica carries no next_tick_at attribute


class RoutedRequest:
    """The caller's handle on one routed request: ``tokens`` /
    ``finished`` mirror :class:`~.serving.Request`, plus the routing
    story — which replica serves it (``replica``), whether a hedge
    fired (``hedged``) and which leg won (``outcome``), how often it
    was re-routed off a dead replica (``rerouted``), and the
    router-clock latency stamps (``t_submit`` / ``t_admitted`` /
    ``t_first_token`` / ``t_done``; ``ttft`` and ``latency`` derived).

    ``outcome`` at completion: ``"ok"`` (primary leg, no drama),
    ``"hedge_won"`` (the hedge leg's first token beat the primary),
    ``"hedged"`` (a hedge fired but the primary still won),
    ``"rerouted"`` (the request survived at least one replica death),
    or ``"shed"`` (refused at the door by name — the request never
    reached a replica; ``replica`` stays None, and ``shed_reason``
    carries the name: ``"budget"`` for an over-budget sheddable
    tenant, ``"overload"``/``"overload_hard"`` for the queue-depth
    ceilings. The chaos plane's shed-by-name contract — graftcheck
    GC010 — is that no request is ever shed without one).

    ``tenant`` names the contract the request is billed to (the QoS
    plane); None on routers without ``qos=``.
    """

    __slots__ = (
        "id", "prompt", "max_new", "key", "tenant", "t_submit",
        "t_admitted", "t_first_token", "t_done", "replica",
        "hedge_replica", "hedged", "rerouted", "migrated", "finished",
        "outcome", "shed_reason", "trace", "_legs", "_hedge_charged",
    )

    _next_id = 0

    def __init__(self, prompt, max_new: int, key, t_submit: float,
                 tenant: str | None = None):
        if max_new < 1:
            # a 0-token request can never produce the first token the
            # router resolves on — it would sit in the awaiting books
            # forever (serving.Request enforces the same floor)
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        self.id = RoutedRequest._next_id
        RoutedRequest._next_id += 1
        self.prompt = prompt
        self.max_new = int(max_new)
        self.key = key
        self.tenant = tenant
        self.t_submit = float(t_submit)
        self._hedge_charged = False  # holds one hedge-entitlement unit
        self.t_admitted: float | None = None
        self.t_first_token: float | None = None
        self.t_done: float | None = None
        self.replica: int | None = None      # current primary replica
        self.hedge_replica: int | None = None
        self.hedged = False
        self.rerouted = 0
        self.migrated = False  # the stream moved tiers (two_tier)
        self.finished = False
        self.outcome: str | None = None
        self.shed_reason: str | None = None  # set iff outcome "shed"
        self.trace: int | None = None  # TraceBook id (None = dark)
        # (replica_idx, scheduler_request) in dispatch order; the
        # winner leg is promoted to index 0 when first tokens resolve
        self._legs: list[tuple[int, Any]] = []

    @property
    def tokens(self):
        """The winning leg's token stream (the primary's until a hedge
        resolves). Empty before the first token."""
        return self._legs[0][1].tokens if self._legs else []

    @property
    def ttft(self) -> float | None:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def latency(self) -> float | None:
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit

    def __repr__(self) -> str:
        state = self.outcome if self.finished else "in-flight"
        return (
            f"RoutedRequest(id={self.id}, replica={self.replica}, "
            f"{state})"
        )


class _RouterObs:
    """Instrument bundle resolved once at construction (the
    ``_ServingObs`` discipline): the routing path only increments.
    Built when a registry or flight recorder is attached; a dark
    router's submit/step do no observability work beyond ``is None``
    checks."""

    def __init__(self, router: "RequestRouter", registry, flight):
        self.flight = flight
        # tenant-labeled series only exist on a qos= router — a
        # tenant-less router's series keep their pre-QoS label sets
        self._tenantful = router._qos is not None
        self._r = registry is not None
        if not self._r:
            self.registry = None
            return
        self.registry = registry
        self.policy = router.policy
        # outcome-labeled completions, series created lazily per
        # (replica, outcome[, tenant]) and cached — label churn is
        # tiny (N x 4 x tenants)
        self._done: dict[tuple, Any] = {}
        # shed-by-name counters exist on EVERY instrumented router:
        # the overload ceilings shed tenantless traffic too, and the
        # chaos invariant (no unnamed drops) reads the reason label
        self._shed_by_reason: dict[str, Any] = {}
        if self._tenantful:
            self._q_shed: dict[tuple[str, str], Any] = {}
            self._q_ttft: dict[str, Any] = {}
            self._q_hedge_ref: dict[str, Any] = {}
        self.m_partition = registry.counter(
            "router_partitions_total",
            help="router<->replica network partitions begun",
        )
        self.m_hedge = registry.counter(
            "router_hedge_fired_total",
            help="TTFT-deadline hedges dispatched (hedge_p99 policy)",
        )
        self.m_eject = registry.counter(
            "router_replica_ejections_total",
            help="replicas ejected from routing on a health flip",
        )
        self.m_queue_wait = registry.histogram(
            "router_queue_wait_seconds",
            help="submit -> scheduler admission (first prefill chunk)",
        )
        self.m_ttft = registry.histogram(
            "router_ttft_seconds",
            help="submit -> first token, across hedges and re-routes",
        )
        # busy chip-time (admission -> done): the cost-ledger plane's
        # source series — per tenant on qos routers, router-wide
        # always (the windowed SLO layer attributes these per window)
        self.m_busy = registry.counter(
            "router_busy_seconds_total",
            help="admission -> completion chip-time, all requests",
        )
        if self._tenantful:
            self._q_busy: dict[str, Any] = {}
        self.m_depth = [
            registry.gauge(
                "router_replica_depth",
                help="queued + active requests on the replica",
                replica=str(i),
            )
            for i in range(len(router.replicas))
        ]
        self.m_routable = registry.gauge(
            "router_routable_replicas",
            help="replicas currently admitting traffic",
        )
        # disaggregation series (two_tier only): the handoff plane's
        # whole telemetry budget lives here — ONE counting point for
        # live tier wrappers and sim replicas alike, since every
        # migration flows through the router's book
        self._two_tier = router.policy == "two_tier"
        if self._two_tier:
            self._mig: dict[str, Any] = {}  # reason -> counter
            self.m_mig_pages = registry.counter(
                "disagg_migrated_pages_total",
                help="KV pages moved prefill -> decode",
            )
            self.m_mig_bytes = registry.counter(
                "disagg_migrated_bytes_total",
                help="KV payload bytes moved prefill -> decode",
            )
            self.m_mig_s = registry.histogram(
                "disagg_migration_seconds",
                help="capture -> adoption, router clock",
            )
            self.m_tier_depth = {
                t: registry.gauge(
                    "disagg_tier_depth",
                    help="queued + active requests on the tier",
                    tier=t,
                )
                for t in ("prefill", "decode")
            }

    def completed(self, rr: RoutedRequest) -> None:
        if not self._r:
            return
        # the tenant label rides router_requests_total on qos routers
        # only — same lazy per-labelset cache, one more key element
        labels = {"replica": str(int(rr.replica)),
                  "outcome": str(rr.outcome)}
        if self._tenantful:
            labels["tenant"] = (
                rr.tenant if rr.tenant is not None else "-"
            )
        key = tuple(labels.values())
        c = self._done.get(key)
        if c is None:
            c = self._done[key] = self.registry.counter(
                "router_requests_total",
                help="routed requests completed",
                policy=self.policy, **labels,
            )
        c.inc()
        if self._tenantful and rr.ttft is not None \
                and rr.tenant is not None:
            h = self._q_ttft.get(rr.tenant)
            if h is None:
                h = self._q_ttft[rr.tenant] = (
                    self.registry.histogram(
                        "qos_ttft_seconds",
                        help="submit -> first token, per tenant",
                        tenant=rr.tenant,
                    )
                )
            h.observe(rr.ttft)
        if rr.ttft is not None:
            self.m_ttft.observe(rr.ttft)
        if rr.t_done is not None and rr.t_admitted is not None:
            busy = rr.t_done - rr.t_admitted
            if busy > 0:
                self.m_busy.inc(busy)
                if self._tenantful and rr.tenant is not None:
                    b = self._q_busy.get(rr.tenant)
                    if b is None:
                        b = self._q_busy[rr.tenant] = (
                            self.registry.counter(
                                "qos_busy_seconds_total",
                                help="admission -> completion "
                                "chip-time, per tenant",
                                tenant=rr.tenant,
                            )
                        )
                    b.inc(busy)

    def shed(self, rr: RoutedRequest, reason: str, t: float) -> None:
        """One request refused at the door by name (over-budget
        sheddable tenant, or an overload queue-depth ceiling): the
        per-reason counter (every router), the per-(tenant, reason)
        counter (qos routers), plus the flight-recorder instant
        event."""
        if self._r:
            c = self._shed_by_reason.get(reason)
            if c is None:
                c = self._shed_by_reason[reason] = (
                    self.registry.counter(
                        "router_shed_total",
                        help="requests shed at the router door, by "
                        "reason — the shed-by-name contract's tally",
                        reason=str(reason),
                    )
                )
            c.inc()
            if self._tenantful:
                key = (str(rr.tenant), str(reason))
                qc = self._q_shed.get(key)
                if qc is None:
                    qc = self._q_shed[key] = self.registry.counter(
                        "qos_shed_total",
                        help="requests shed at the router door, by "
                        "tenant and reason",
                        tenant=key[0], reason=key[1],
                    )
                qc.inc()
        if self.flight is not None:
            if rr.tenant is not None:
                self.flight.event(
                    "qos shed", src="router", t=t, request=rr.id,
                    tenant=str(rr.tenant), reason=str(reason),
                )
            else:
                # tenant-less shed: no tenant label at all — a
                # literal "None" masquerading as a tenant name would
                # poison the postmortem record
                self.flight.event(
                    "request shed", src="router", t=t,
                    request=rr.id, reason=str(reason),
                )

    def hedge_refused(self, rr: RoutedRequest, t: float) -> None:
        if self._r:
            c = self._q_hedge_ref.get(rr.tenant)
            if c is None:
                c = self._q_hedge_ref[rr.tenant] = (
                    self.registry.counter(
                        "qos_hedge_refused_total",
                        help="due hedges refused: the tenant was at "
                        "its outstanding-hedge entitlement",
                        tenant=str(rr.tenant),
                    )
                )
            c.inc()

    def admitted(self, wait_s: float) -> None:
        if self._r:
            self.m_queue_wait.observe(wait_s)

    def hedge_fired(self, rr: RoutedRequest, replica: int,
                    t: float) -> None:
        if self._r:
            self.m_hedge.inc()
        if self.flight is not None:
            self.flight.event(
                "hedge fired", src="router", t=t, request=rr.id,
                primary=rr.replica, hedge=replica,
            )

    def ejected(self, i: int, t: float, rerouted: int) -> None:
        if self._r:
            self.m_eject.inc()
        if self.flight is not None:
            self.flight.event(
                "replica ejected", src="router", t=t, replica=i,
                rerouted=rerouted,
            )

    def restored(self, i: int, t: float) -> None:
        if self.flight is not None:
            self.flight.event(
                "replica restored", src="router", t=t, replica=i
            )

    def partitioned(self, i: int, t: float, rerouted: int) -> None:
        """A router<->replica partition began: the replica keeps
        ticking, its results are unreachable, its in-flight requests
        re-route (legs abandoned UNCANCELLED — no cancel can cross a
        partition)."""
        if self._r:
            self.m_partition.inc()
        if self.flight is not None:
            self.flight.event(
                "replica partitioned", src="router", t=t, replica=i,
                rerouted=rerouted,
            )

    def healed(self, i: int, t: float, stale_cancelled: int) -> None:
        if self.flight is not None:
            self.flight.event(
                "partition healed", src="router", t=t, replica=i,
                stale_cancelled=stale_cancelled,
            )

    def migrated(self, rr: RoutedRequest, ticket, j: int, t: float,
                 dur: float) -> None:
        """One landed handoff: counters by reason, the page/byte
        tallies the PERF byte model prices, the capture->adoption
        latency, and the flight-recorder instant event."""
        if self._r:
            reason = str(getattr(ticket, "reason", "prefill_done"))
            c = self._mig.get(reason)
            if c is None:
                c = self._mig[reason] = self.registry.counter(
                    "disagg_migrations_total",
                    help="KV-page migrations landed on the decode tier",
                    reason=reason,
                )
            c.inc()
            self.m_mig_pages.inc(int(getattr(ticket, "pages", 0)))
            self.m_mig_bytes.inc(int(getattr(ticket, "nbytes", 0)))
            self.m_mig_s.observe(dur)
        if self.flight is not None:
            self.flight.event(
                "kv migrated", src="router", t=t, request=rr.id,
                dest=j, pages=int(getattr(ticket, "pages", 0)),
                nbytes=int(getattr(ticket, "nbytes", 0)),
            )

    def depths(self, router: "RequestRouter") -> None:
        if not self._r:
            return
        for i, r in enumerate(router.replicas):
            self.m_depth[i].set(r.pending + r.active)
        self.m_routable.set(len(router.routable_replicas))
        if self._two_tier:
            for t, members in (
                ("prefill", router._prefill_set),
                ("decode", router._decode_set),
            ):
                self.m_tier_depth[t].set(sum(
                    router.replicas[i].pending
                    + router.replicas[i].active
                    for i in members
                ))


class RequestRouter:
    """Admission router over N scheduler replicas (module docstring:
    policies, replica protocol, clock semantics).

    >>> router = RequestRouter([s0, s1, s2, s3], policy="least_loaded")
    >>> rr = router.submit(prompt, max_new=64)     # open-loop arrivals
    >>> while not rr.finished:
    ...     router.step()                          # tick the fleet
    >>> rr.tokens, rr.ttft

    ``step()`` is one fleet tick: probe replica health (eject / restore
    + re-route off the dead), tick every busy routable replica, resolve
    first tokens and completions, and fire due TTFT hedges. The caller
    owns the cadence — a live serving loop calls it hot, a virtual-time
    driver (:func:`~..sim.workload.run_router_day`) advances the clock
    to :meth:`next_event_at` between calls.

    ``health_fn(replica) -> bool`` decides routability (default: the
    replica's ``alive`` attribute, True when absent); ``mark_down`` /
    ``mark_up`` override it manually, and an ejected replica's
    in-flight requests are re-routed the moment the flip is seen —
    zero dropped requests under a replica kill, pinned by
    tests/test_router.py. ``ttft_slo`` (required for ``hedge_p99``,
    ignored otherwise) is the per-request first-token budget in clock
    seconds."""

    def __init__(
        self,
        replicas: Sequence[Any],
        *,
        policy: str = "least_loaded",
        ttft_slo: float | None = None,
        clock=None,
        health_fn: Callable[[Any], bool] | None = None,
        migrate_threshold_bytes: int | None = None,
        migrate_gbs: float | None = None,
        qos: TenantRegistry | None = None,
        shed_depth: int | None = None,
        shed_depth_hard: int | None = None,
        registry=None,
        flight=None,
        exporter=None,
        trace=None,
    ):
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("a router needs at least one replica")
        if policy not in ROUTER_POLICIES:
            raise ValueError(
                f"unknown policy {policy!r}; choose one of "
                f"{ROUTER_POLICIES}"
            )
        if policy == "hedge_p99":
            if ttft_slo is None or ttft_slo <= 0:
                raise ValueError(
                    "hedge_p99 needs ttft_slo > 0: the policy IS the "
                    "deadline (re-dispatch when the first token misses "
                    "it)"
                )
        self.policy = policy
        # disaggregated placement: the fleet must actually be two
        # tiers, and the router keeps the membership sets (replica
        # `tier` attributes, models/disagg.py's wrappers and the sim's
        # two-tier SimReplica both stamp them)
        self._prefill_set: set[int] = set()
        self._decode_set: set[int] = set()
        if policy == "two_tier":
            for i, r in enumerate(self.replicas):
                t = getattr(r, "tier", "unified")
                if t == "prefill":
                    self._prefill_set.add(i)
                elif t == "decode":
                    self._decode_set.add(i)
            if not self._prefill_set or not self._decode_set:
                raise ValueError(
                    "two_tier needs at least one replica in EACH tier "
                    f"(got {len(self._prefill_set)} prefill, "
                    f"{len(self._decode_set)} decode); tag replicas "
                    "with tier='prefill'/'decode' "
                    "(models/disagg.py wrappers, or SimReplica(tier=))"
                )
        self.migrate_threshold_bytes = (
            None if migrate_threshold_bytes is None
            else int(migrate_threshold_bytes)
        )
        self.migrate_gbs = (
            None if migrate_gbs is None else float(migrate_gbs)
        )
        # in-flight migrations: rr -> [ticket, ready_at, t_captured]
        # (insertion-ordered like every router book)
        self._migrating: dict[RoutedRequest, list] = {}
        # inert unless hedging: the sim driver schedules wakeups off
        # this, and a non-hedging router must not generate deadline
        # events nothing will consume
        self.ttft_slo = (
            float(ttft_slo) if policy == "hedge_p99" else None
        )
        self.clock = clock
        self._now = (
            time.perf_counter if clock is None else clock.now
        )
        self._health_fn = health_fn  # None = the default `alive` probe
        self._up = [True] * len(self.replicas)
        self._routable: list[int] = list(range(len(self.replicas)))
        self._down_manual: set[int] = set()
        # network partitions (chaos plane): a partitioned replica is
        # unroutable but ALIVE — it keeps ticking, its results are
        # unreachable, and heal() reconciles its stale legs so a
        # rejoin can never double-retire a request
        self._partitioned: set[int] = set()
        self._partition_stale: dict[int, list] = {}
        self.n_partitions = 0
        self.n_partitions_healed = 0
        self.n_stale_cancelled = 0
        # overload shedding (chaos plane): with a soft queue-depth
        # ceiling, sheddable (batch-class; ALL classless) traffic is
        # shed by name once the fleet's queued depth reaches it; the
        # hard ceiling (default 2x soft) sheds EVERY class — the
        # bounded-queue guarantee under offered load past 1. None
        # keeps the pre-chaos queue-without-bound behavior.
        if shed_depth is not None and shed_depth < 1:
            raise ValueError(
                f"shed_depth must be >= 1 or None, got {shed_depth}"
            )
        if shed_depth_hard is not None and shed_depth is None:
            raise ValueError(
                "shed_depth_hard without shed_depth: the hard ceiling "
                "refines the soft one, it cannot stand alone"
            )
        self.shed_depth = None if shed_depth is None else int(shed_depth)
        self.shed_depth_hard = (
            None if shed_depth is None
            else int(shed_depth_hard) if shed_depth_hard is not None
            else 2 * int(shed_depth)
        )
        if (self.shed_depth_hard is not None
                and self.shed_depth_hard < self.shed_depth):
            raise ValueError(
                f"shed_depth_hard ({self.shed_depth_hard}) below "
                f"shed_depth ({self.shed_depth}): the hard ceiling "
                "must sit at or above the soft one"
            )
        self._rr = 0
        # in-flight request books, all insertion-ordered dicts (used as
        # ordered sets): hash-order iteration would break bit-identical
        # sim replays. _awaiting holds requests with no first token yet
        # (keyed per replica leg); _streaming holds requests past first
        # token, keyed by the winning replica.
        self._awaiting: list[dict[RoutedRequest, None]] = [
            {} for _ in self.replicas
        ]
        self._streaming: list[dict[RoutedRequest, None]] = [
            {} for _ in self.replicas
        ]
        self._orphans: dict[RoutedRequest, None] = {}
        self._hedge = RequestHedge()
        self.n_submitted = 0
        self.n_completed = 0
        self.n_hedges = 0
        self.n_rerouted = 0
        self.n_migrated = 0
        self.n_kept_local = 0  # threshold / no-decode-replica keeps
        self.n_bounced = 0  # captured but decode tier could never fit
        self.migrated_bytes = 0
        # multi-tenant QoS (opt-in, qos/ package): token buckets
        # charged at submit (over-budget batch work is shed by name),
        # and per-tenant TTFT-hedge entitlements (a tenant's deadline
        # panic draws from its OWN slack, counted and refused beyond
        # it — module docstring "priced isolation")
        self._qos = qos
        if qos is not None and len(qos) == 0:
            raise ValueError(
                "qos= needs at least one TenantContract registered: "
                "an empty registry can route nothing"
            )
        self._buckets = qos.buckets() if qos is not None else {}
        self._hedges_out: dict[str, int] = {}
        self.n_shed = 0
        self.n_hedges_refused = 0
        self.n_over_budget = 0  # non-sheddable classes: paced, not shed
        self._obs = (
            _RouterObs(self, registry, flight)
            if registry is not None or flight is not None
            else None
        )
        # causal tracing (round 22): OPT-IN per the GC004 contract —
        # a dark router pays one `is None` check per transition
        self._trace = trace
        if trace is not None:
            self._propagate_trace(trace)
        # initial health reading: a replica dead at construction must
        # never receive the first submit (step() keeps probing after)
        for i, r in enumerate(self.replicas):
            self._up[i] = self._probe(r)
        self._routable = [i for i, u in enumerate(self._up) if u]
        if exporter is not None:
            exporter.register_router(self)

    # -- causal tracing (round 22) --------------------------------------

    def attach_trace(self, book) -> None:
        """Arm causal tracing post-construction — the chaos injector's
        hook (``scenario.build`` signatures stay untouched): every
        request submitted from here on mints a trace id at the door,
        and the replica-side events (DRR, prefill chunks) stamp the
        same book."""
        self._trace = book
        self._propagate_trace(book)

    def _propagate_trace(self, book) -> None:
        for rep in self.replicas:
            at = getattr(rep, "attach_trace", None)
            if at is not None:
                at(book)

    def inflight_on(self, i: int) -> list[RoutedRequest]:
        """Snapshot of the requests with a leg on replica ``i`` — the
        fleet controller reads this at a shrink to stamp
        ``evacuated_on_resize`` on the traces it is about to drain."""
        return list(self._awaiting[i]) + list(self._streaming[i])

    # -- health ---------------------------------------------------------

    @property
    def routable_replicas(self) -> list[int]:
        """Indices currently admitting traffic (healthy + not manually
        marked down). Cached — rebuilt only on a health flip; this sits
        on the per-event hot path of million-request sims."""
        return self._routable

    @property
    def in_flight(self) -> int:
        return self.n_submitted - self.n_completed

    def mark_down(self, i: int) -> None:
        """Manually eject replica ``i`` (an operator drain, a bench
        kill): takes effect at the next :meth:`step`'s health probe."""
        self._down_manual.add(int(i))

    def mark_up(self, i: int) -> None:
        self._down_manual.discard(int(i))

    @property
    def queue_depth(self) -> int:
        """Queued (not yet admitted) requests over the ROUTABLE
        fleet — the exact quantity the overload ceilings bound, so
        the chaos plane's bounded-queue probe and the shed door can
        never disagree. Non-routable replicas are excluded by
        construction: a dead replica's queue is wiped, and a
        partitioned replica's frozen backlog (its abandoned,
        uncancelled legs) is bounded by what was in flight at
        partition onset — no new work ever lands there."""
        reps = self.replicas
        return sum(reps[i].pending for i in self._routable)

    # -- network partitions (chaos plane) -------------------------------

    def partition(self, i: int) -> None:
        """Begin a router<->replica network partition: replica ``i``
        becomes unroutable, but — unlike a death — it KEEPS TICKING
        (``step`` still drives it; in-flight work on it progresses and
        burns its capacity). Its in-flight requests re-route onto the
        survivors like an ejection, except their legs on ``i`` are
        abandoned UNCANCELLED: no cancel can cross a partition. The
        abandoned legs are remembered and reconciled at :meth:`heal`,
        so the rejoin can never double-retire a request."""
        i = int(i)
        if not 0 <= i < len(self.replicas):
            raise ValueError(f"partition({i}): no such replica")
        if i in self._partitioned:
            raise ValueError(
                f"partition({i}): replica {i} is already partitioned"
            )
        now = self._now()
        self._partitioned.add(i)
        self.n_partitions += 1
        # fleet prefix cache (cache/ package): a partitioned replica
        # can neither serve nor issue peer-page fetches — the hub
        # fails those fetches to re-prefill until heal()
        _c = getattr(self.replicas[i], "cache", None)
        if _c is not None:
            _c.partition(self.replicas[i].cache_name)
        moved = 0
        if self._up[i]:
            self._up[i] = False
            self._routable = [
                j for j, u in enumerate(self._up) if u
            ]
            moved = self._evacuate_unreachable(i, now)
        if self._obs is not None:
            self._obs.partitioned(i, now, moved)

    def heal(self, i: int) -> None:
        """End replica ``i``'s partition and reconcile: the re-routed
        copies are authoritative — every stale leg the replica still
        holds is cancelled, and legs it finished behind the partition
        are discarded (their tokens were unreachable when produced).
        The request-level books were already detached at
        :meth:`partition`, so nothing the isolated side did can
        complete a request a second time; ``n_stale_cancelled``
        counts the withdrawn legs."""
        i = int(i)
        if i not in self._partitioned:
            raise ValueError(
                f"heal({i}): replica {i} is not partitioned"
            )
        now = self._now()
        self._partitioned.discard(i)
        stale = self._partition_stale.pop(i, [])
        replica = self.replicas[i]
        cancelled = 0
        for rr, leg in stale:
            if getattr(leg, "finished", False):
                continue  # finished behind the partition: discarded
            try:
                if replica.cancel(leg):
                    cancelled += 1
            except Exception:  # noqa: BLE001 — replica died partitioned
                pass
        self.n_stale_cancelled += cancelled
        self.n_partitions_healed += 1
        _c = getattr(replica, "cache", None)
        if _c is not None:
            _c.heal(replica.cache_name)
        up = i not in self._down_manual and self._probe(replica)
        if up and not self._up[i]:
            self._up[i] = True
            self._routable = [
                j for j, u in enumerate(self._up) if u
            ]
        if self._obs is not None:
            self._obs.healed(i, now, cancelled)

    def _evacuate_unreachable(self, i: int, now: float) -> int:
        """The partition twin of :meth:`_evacuate`: requests with a
        leg on unreachable replica ``i`` lose that leg WITHOUT a
        cancel (the cancel cannot be delivered) — the abandoned legs
        are parked in the partition-stale book for :meth:`heal` to
        withdraw. Single-leg requests re-route (zero drops, the
        ejection contract)."""
        moved = 0
        stale = self._partition_stale.setdefault(i, [])
        victims = list(self._awaiting[i]) + list(self._streaming[i])
        self._awaiting[i].clear()
        self._streaming[i].clear()
        for rr in victims:
            for j, leg in rr._legs:
                if j == i:
                    stale.append((rr, leg))
            rr._legs = [leg for leg in rr._legs if leg[0] != i]
            if self._trace is not None and rr.trace is not None:
                self._trace.event(
                    rr.trace, "partition_abandoned", now, replica=i
                )
                if (rr.hedged and rr.t_first_token is None
                        and rr.hedge_replica is not None):
                    self._trace.event(
                        rr.trace, "hedge_abandoned", now, replica=i
                    )
            self._hedge_release(rr)  # the hedge episode died with a leg
            if rr._legs:
                j = rr._legs[0][0]
                if rr.t_first_token is None:
                    rr.replica = j
                    rr.hedge_replica = None
                continue
            self._hedge.disarm(rr)
            self._reroute(rr, now)
            moved += 1
        return moved

    def set_policy(self, policy: str) -> None:
        """Switch the placement policy mid-run — the fleet
        controller's re-policy hook (``fleet/controller.py`` applies
        the ``sweep_router_policy`` winner at each resize's operating
        point). Only the STATELESS placement policies are switchable:
        ``hedge_p99`` and ``two_tier`` are structural (the TTFT
        deadline / the tier membership sets are construction-time
        contracts), so switching into or out of them is refused by
        name, never coerced. In-flight requests are unaffected —
        ``policy`` is read per submit."""
        policy = str(policy)
        if policy == self.policy:
            return
        if policy not in ROUTER_POLICIES:
            raise ValueError(
                f"unknown policy {policy!r}; choose one of "
                f"{ROUTER_POLICIES}"
            )
        structural = {"hedge_p99", "two_tier"}
        if policy in structural or self.policy in structural:
            raise ValueError(
                f"set_policy({policy!r}) refused: "
                f"{(policy if policy in structural else self.policy)!r}"
                " is structural — hedge_p99's ttft_slo and two_tier's "
                "tier membership are construction-time contracts; "
                "build a router with the policy instead of switching "
                "mid-run"
            )
        self.policy = policy
        if self._obs is not None and self._obs.registry is not None:
            # completions must label the policy that ROUTED them: the
            # obs bundle caches the label and its per-(replica,
            # outcome) series — both roll over with the switch
            self._obs.policy = policy
            self._obs._done = {}

    def replica_statuses(
        self, *, max_tick_age_s: float = 30.0
    ) -> list[tuple[bool, str]]:
        """Per-replica (routable, detail) pairs for the aggregate
        ``/healthz`` check — routability as the router currently sees
        it, plus ``last_tick_at`` freshness detail where the replica
        stamps it (wall-clock routers only: a virtual-time replica's
        stamp is on the virtual axis and ages meaninglessly against
        ``perf_counter``)."""
        out = []
        for i, r in enumerate(self.replicas):
            if not self._up[i]:
                out.append((False, "ejected"))
                continue
            last = getattr(r, "last_tick_at", None)
            if self.clock is None and last is not None:
                age = time.perf_counter() - last
                busy = (r.pending + r.active) > 0
                if busy and age > max_tick_age_s:
                    out.append(
                        (False, f"stale: last tick {age:.1f}s ago")
                    )
                    continue
                out.append((True, f"ok, last tick {age:.1f}s ago"))
                continue
            out.append((True, "ok"))
        return out

    def _probe(self, r) -> bool:
        hf = self._health_fn
        return getattr(r, "alive", True) if hf is None else bool(hf(r))

    def _probe_health(self) -> None:
        now = None
        hf = self._health_fn
        dm = self._down_manual
        parts = self._partitioned
        downs: list[int] | None = None
        for i, r in enumerate(self.replicas):
            # default probe inlined: this loop runs once per step of a
            # million-event sim, and a per-replica function call
            # measured ~10% of the whole day. A partitioned replica is
            # pinned down until heal() — the probe must not flip it
            # back while its stale legs are unreconciled.
            up = i not in dm and i not in parts and (
                getattr(r, "alive", True) if hf is None else bool(hf(r))
            )
            if up == self._up[i]:
                continue
            if now is None:
                now = self._now()
            self._up[i] = up
            self._routable = [
                j for j, u in enumerate(self._up) if u
            ]
            if up:
                if self._obs is not None:
                    self._obs.restored(i, now)
            else:
                # evacuation is DEFERRED to after the full scan: a
                # CORRELATED kill flips several replicas in one probe
                # pass, and evacuating at the first flip would re-route
                # onto a same-instant casualty still marked routable
                # (the chaos plane's correlated-host-kill episode
                # caught exactly this)
                if downs is None:
                    downs = []
                downs.append(i)
        if downs is not None:
            for i in downs:
                n = self._evacuate(i, now)
                if self._obs is not None:
                    self._obs.ejected(i, now, n)

    def _evacuate(self, i: int, now: float) -> int:
        """Replica ``i`` went down: every in-flight request with a leg
        on it loses that leg; single-leg requests are re-routed onto
        the survivors (or parked until one returns — zero drops either
        way)."""
        moved = 0
        victims = list(self._awaiting[i]) + list(self._streaming[i])
        self._awaiting[i].clear()
        self._streaming[i].clear()
        replica = self.replicas[i]
        for rr in victims:
            for j, leg in rr._legs:
                if j != i:
                    continue
                # best-effort cancel: a DRAINED-but-alive replica (an
                # operator mark_down, a transient health flip) must not
                # keep decoding streams nobody reads — zombie legs
                # occupy slots (and, paged, pool pages) for their whole
                # budget and skew least_loaded on resume. A truly dead
                # replica may raise or no-op; either is fine, the leg
                # is abandoned regardless.
                try:
                    replica.cancel(leg)
                except Exception:  # noqa: BLE001 — dead replica
                    pass
            rr._legs = [leg for leg in rr._legs if leg[0] != i]
            if self._trace is not None and rr.trace is not None:
                self._trace.event(
                    rr.trace, "evacuated", now, replica=i
                )
                if (rr.hedged and rr.t_first_token is None
                        and rr.hedge_replica is not None):
                    # the hedge EPISODE died with the leg (whichever
                    # side was lost): neither won nor race-cancelled
                    # — the audit's third hedge-leg disposition
                    self._trace.event(
                        rr.trace, "hedge_abandoned", now, replica=i
                    )
            self._hedge_release(rr)  # the hedge episode died with a leg
            if rr._legs:
                # the surviving hedge leg carries the request alone
                j = rr._legs[0][0]
                if rr.t_first_token is None:
                    rr.replica = j
                    rr.hedge_replica = None
                continue
            self._hedge.disarm(rr)
            self._reroute(rr, now)
            moved += 1
        return moved

    def _reroute(self, rr: RoutedRequest, now: float) -> None:
        routable = self.routable_replicas
        rr.rerouted += 1
        self.n_rerouted += 1
        rr.t_first_token = None  # the stream restarts from the prompt
        rr.t_admitted = None
        if not routable:
            # nobody to route to RIGHT NOW: park it; each step retries
            # once a replica recovers — the request is never dropped
            self._orphans[rr] = None
            return
        j = self._pick(rr.prompt, routable)
        leg = self._submit_leg(j, rr)
        rr._legs = [(j, leg)]
        rr.replica = j
        rr.hedge_replica = None
        self._awaiting[j][rr] = None
        if self._trace is not None and rr.trace is not None:
            self._trace.event(rr.trace, "rerouted", now, replica=j)
        if self.policy == "hedge_p99":
            self._hedge.arm(rr, now + self.ttft_slo)
            if self._trace is not None and rr.trace is not None:
                self._trace.event(
                    rr.trace, "hedge_armed", now,
                    fire_at=now + self.ttft_slo,
                )

    # -- policy ---------------------------------------------------------

    def _load(self, i: int) -> int:
        r = self.replicas[i]
        return r.pending + r.active

    def _affinity(self, i: int, prompt) -> int:
        """Resident-prefix score of ``prompt`` on replica ``i``: the
        replica's own ``prefix_hits`` when it has one (the sim
        shortcut), else the number of leading
        :func:`~.paging.prefix_page_digests` pages already resident in
        its paged pool — exactly the pages admission would share."""
        r = self.replicas[i]
        hits = getattr(r, "prefix_hits", None)
        if hits is not None:
            return int(hits(prompt))
        pool = getattr(r, "pool", None)
        if pool is None or not getattr(r, "paged", False):
            return 0
        p = np.asarray(prompt, np.int32).reshape(-1)
        digests = prefix_page_digests(p, r.P, r.max_pages)
        n = 0
        for d in digests[: max(p.size - 1, 0) // r.P]:
            if pool.lookup(d) is None:
                break
            n += 1
        return n

    def _least_loaded(self, routable: list[int]) -> int:
        # hand-rolled argmin: this runs once per submit in the
        # million-request sims, where a key-lambda min measured ~3x
        best, best_load = routable[0], None
        for i in routable:
            r = self.replicas[i]
            load = r.pending + r.active
            if best_load is None or load < best_load:
                best, best_load = i, load
        return best

    def _pick(self, prompt, routable: list[int]) -> int:
        if self.policy == "two_tier":
            # fresh requests prefill-tier least_loaded; when the whole
            # prefill tier is out, any routable replica serves
            # (availability over tier purity — the decode wrappers are
            # complete schedulers)
            pre = [i for i in routable if i in self._prefill_set]
            return self._least_loaded(pre if pre else routable)
        if self.policy == "round_robin":
            n = len(self.replicas)
            for d in range(n):
                i = (self._rr + d) % n
                if i in routable:
                    self._rr = (i + 1) % n
                    return i
        if self.policy == "prefix_affinity":
            return self._bounded_affinity(prompt, routable)
        # least_loaded — also hedge_p99's placement policy
        return self._least_loaded(routable)

    def _bounded_affinity(self, prompt, cands: list[int]) -> int:
        """The resident-prefix replica (longest registered prefix-digest
        chain, the pages a placement would SHARE), load-bounded:
        affinity wins only while its load stays within one slot batch
        of the least loaded. Unbounded affinity melts a replica under a
        hot system prompt (a 0.7 share rate aimed 70% of the fleet's
        traffic at one quarter of its capacity — p99 went 100x,
        measured); the bound diverts the overflow to least_loaded,
        trading those requests' prefill skip for the fleet's tail.
        Both the ``prefix_affinity`` submit path and two-tier decode
        placement route here — one bound, not two copies."""
        aff, aff_score = None, 0
        for i in cands:
            sc = self._affinity(i, prompt)
            if sc > aff_score or (
                sc == aff_score and sc > 0
                and self._load(i) < self._load(aff)
            ):
                aff, aff_score = i, sc
        ll = self._least_loaded(cands)
        if aff is None or aff_score == 0:
            return ll
        slack = getattr(self.replicas[aff], "S", 1)
        if self._load(aff) <= self._load(ll) + slack:
            return aff
        return ll

    # -- the request path -----------------------------------------------

    @staticmethod
    def _prompt_tokens(prompt) -> int:
        """Token length of a prompt in any of the entry-door shapes:
        a SimPrompt descriptor (``length``), a bare int (the sim
        protocol's "a prompt of that many tokens" shorthand —
        ``np.size`` would read it as ONE token and undercharge the
        budget door ~100x), or a token array/list."""
        n = getattr(prompt, "length", None)
        if n is not None:
            return int(n)
        if isinstance(prompt, (int, np.integer)):
            return int(prompt)
        return int(np.size(prompt))

    def _submit_leg(self, j: int, rr: RoutedRequest):
        """One replica-submit with the tenant threaded through —
        only when the request carries one, so tenant-less traffic
        keeps the pre-QoS replica protocol verbatim."""
        if rr.trace is None:
            # dark path: the pre-trace replica protocol verbatim
            if rr.tenant is None:
                return self.replicas[j].submit(
                    rr.prompt, rr.max_new, key=rr.key
                )
            return self.replicas[j].submit(
                rr.prompt, rr.max_new, key=rr.key, tenant=rr.tenant
            )
        kw = {"trace": rr.trace}
        if rr.tenant is not None:
            kw["tenant"] = rr.tenant
        try:
            # traced path: the id travels IN the submit so the
            # replica's enqueue-time events (drr_queued) carry it
            return self.replicas[j].submit(
                rr.prompt, rr.max_new, key=rr.key, **kw
            )
        except TypeError:
            # foreign replica type without the trace kwarg: submit
            # dark, then stamp the leg post-hoc where possible
            del kw["trace"]
            leg = self.replicas[j].submit(
                rr.prompt, rr.max_new, key=rr.key, **kw
            )
            try:
                leg.trace = rr.trace
            except AttributeError:
                pass
            return leg

    def submit(self, prompt, max_new: int, key=None,
               tenant: str | None = None) -> RoutedRequest:
        """Route one request; returns the live :class:`RoutedRequest`
        whose ``tokens`` / ``finished`` the caller watches. Raises when
        no replica is routable — the condition the aggregate
        ``/healthz`` check reports as 503.

        ``tenant`` is REQUIRED on a ``qos=`` router (unknown tenants
        refused by name). The tenant's token bucket is charged
        ``prompt + max_new`` tokens here, at the door: an over-budget
        tenant whose class is sheddable (``batch``) gets the request
        back immediately with ``outcome == "shed"`` — named, counted
        (``n_shed``, ``qos_shed_total{tenant,reason}``), never routed;
        an over-budget interactive tenant is PACED instead (the
        request routes, and the replicas' deficit admission caps the
        tenant at its weight — counted in ``n_over_budget``)."""
        routable = self.routable_replicas
        if not routable:
            raise RuntimeError(
                f"no routable replicas (0 of {len(self.replicas)} "
                "admittable); repair or mark_up a replica"
            )
        now = self._now()
        contract = None
        if self._qos is not None:
            if tenant is None:
                raise ValueError(
                    "qos router needs tenant= at submit: budgets, "
                    "shed, and hedge entitlements are per-contract "
                    "(register a catch-all TenantContract for "
                    "untagged traffic)"
                )
            contract = self._qos.get(tenant)  # unknown: named KeyError
        if self.shed_depth is not None:
            # overload ceilings (chaos plane): queued depth over the
            # routable fleet (THE queue_depth quantity — one
            # implementation, so the chaos probe and this door can
            # never disagree), read BEFORE this submit queues
            # anything AND before the budget door — an overload shed
            # must not charge a token bucket for work the fleet never
            # accepted (the r19 refund convention: refusals never
            # keep the charge). Soft ceiling sheds sheddable work
            # (batch class; all classless traffic) by name; the hard
            # ceiling sheds every class — shed beats an unbounded
            # queue.
            depth = self.queue_depth
            if depth >= self.shed_depth_hard:
                return self._shed_at_door(
                    prompt, max_new, key, tenant, now, "overload_hard"
                )
            if depth >= self.shed_depth and (
                contract is None or contract.sheddable
            ):
                return self._shed_at_door(
                    prompt, max_new, key, tenant, now, "overload"
                )
        if contract is not None:
            bucket = self._buckets.get(tenant)
            if bucket is not None and not bucket.take(
                self._prompt_tokens(prompt) + int(max_new), now
            ):
                if contract.sheddable:
                    return self._shed_at_door(
                        prompt, max_new, key, tenant, now, "budget"
                    )
                self.n_over_budget += 1
        rr = RoutedRequest(prompt, max_new, key, now, tenant=tenant)
        if self._trace is not None:
            rr.trace = self._trace.mint()
            self._trace.event(
                rr.trace, "submitted", now, tenant=tenant,
                prompt=self._prompt_tokens(prompt),
            )
        i = self._pick(prompt, routable)
        leg = self._submit_leg(i, rr)
        rr._legs = [(i, leg)]
        rr.replica = i
        self._awaiting[i][rr] = None
        if self.policy == "hedge_p99":
            self._hedge.arm(rr, now + self.ttft_slo)
            if rr.trace is not None:
                self._trace.event(
                    rr.trace, "hedge_armed", now,
                    fire_at=now + self.ttft_slo,
                )
        self.n_submitted += 1
        return rr

    def _shed_at_door(self, prompt, max_new: int, key,
                      tenant: str | None, now: float,
                      reason: str) -> RoutedRequest:
        """Refuse one request at the door BY NAME (graftcheck GC010:
        no bare drops): the handle comes back finished with
        ``outcome == "shed"`` and ``shed_reason`` set, counted and
        flight-stamped, never routed."""
        if not reason:
            raise ValueError("a shed needs a non-empty reason")
        rr = RoutedRequest(prompt, max_new, key, now, tenant=tenant)
        rr.finished = True
        rr.outcome = "shed"
        rr.shed_reason = str(reason)
        rr.t_done = now
        if self._trace is not None:
            rr.trace = self._trace.mint()
            self._trace.event(
                rr.trace, "submitted", now, tenant=tenant,
                prompt=self._prompt_tokens(prompt),
            )
            self._trace.event(
                rr.trace, "shed", now, reason=str(reason)
            )
        self.n_submitted += 1
        self.n_completed += 1
        self.n_shed += 1
        if self._obs is not None:
            self._obs.shed(rr, reason, now)
        return rr

    def _hedge_entitled(self, rr: RoutedRequest, now: float) -> bool:
        """May this tenant fire one more hedge? The entitlement is a
        cap on OUTSTANDING hedge legs per tenant (contract ``hedges``;
        None = unlimited): a tenant's deadline panic re-dispatches
        draw from its own pool of slack, counted and refused beyond
        it, so they can never consume another tenant's."""
        if self._qos is None or rr.tenant is None:
            return True
        ent = self._qos.get(rr.tenant).hedges
        if ent is None:
            return True
        out = self._hedges_out.get(rr.tenant, 0)
        if out >= ent:
            self.n_hedges_refused += 1
            if self._obs is not None:
                self._obs.hedge_refused(rr, now)
            return False
        self._hedges_out[rr.tenant] = out + 1
        rr._hedge_charged = True
        return True

    def _hedge_release(self, rr: RoutedRequest) -> None:
        """The hedge episode ended (first token resolved, or the
        hedged request lost a leg): return the entitlement unit."""
        if not rr._hedge_charged:
            return
        rr._hedge_charged = False
        n = self._hedges_out.get(rr.tenant, 0) - 1
        if n > 0:
            self._hedges_out[rr.tenant] = n
        else:
            self._hedges_out.pop(rr.tenant, None)

    def _fire_hedges(self, now: float) -> None:
        if not self._hedge:
            return
        for rr in self._hedge.due(now):
            taken = {i for i, _ in rr._legs}
            cands = [
                i for i in self.routable_replicas if i not in taken
            ]
            if not cands:
                continue  # nowhere to hedge to; the primary stands
            if not self._hedge_entitled(rr, now):
                continue  # over entitlement: the primary stands
            j = self._least_loaded(cands)
            leg = self._submit_leg(j, rr)
            rr._legs.append((j, leg))
            rr.hedge_replica = j
            rr.hedged = True
            self._awaiting[j][rr] = None
            self.n_hedges += 1
            if self._obs is not None:
                self._obs.hedge_fired(rr, j, now)
            if self._trace is not None and rr.trace is not None:
                self._trace.event(
                    rr.trace, "hedge_fired", now, replica=j
                )

    def _resolve_first_tokens(self, now: float,
                              ticked: Sequence[int]) -> None:
        # only replicas that actually ticked can have produced a first
        # token (the 1M-request sim's hot path: the books of the other
        # N-1 replicas must not be rescanned per event); iterate a
        # snapshot — winners mutate the books
        for i in ticked:
            if not self._awaiting[i]:
                continue
            for rr in list(self._awaiting[i]):
                if rr not in self._awaiting[i]:
                    continue  # resolved via its other leg this pass
                winner = None
                for idx, (j, leg) in enumerate(rr._legs):
                    if rr.t_admitted is None and (
                        getattr(leg, "admitted_tick", None) is not None
                    ):
                        rr.t_admitted = now
                        if self._obs is not None:
                            self._obs.admitted(now - rr.t_submit)
                        if (self._trace is not None
                                and rr.trace is not None):
                            self._trace.event(
                                rr.trace, "admitted", now, replica=j
                            )
                    if winner is None and len(leg.tokens) > 0:
                        winner = idx
                if winner is None:
                    continue
                j, leg = rr._legs[winner]
                for k, (jj, loser) in enumerate(rr._legs):
                    if k == winner:
                        continue
                    self._awaiting[jj].pop(rr, None)
                    self.replicas[jj].cancel(loser)
                    if (self._trace is not None
                            and rr.trace is not None
                            and rr.hedged
                            and jj == rr.hedge_replica):
                        # the HEDGE leg lost the race and was reaped:
                        # the "cancelled == fired - won - abandoned"
                        # arithmetic the audit checks counts exactly
                        # these (a reaped PRIMARY is the hedge_won
                        # case, not a cancellation)
                        self._trace.event(
                            rr.trace, "hedge_cancelled", now,
                            replica=jj,
                        )
                rr._legs = [(j, leg)]
                rr.replica = j
                rr.t_first_token = now
                if self._trace is not None and rr.trace is not None:
                    self._trace.event(
                        rr.trace, "first_token", now, replica=j
                    )
                    if rr.hedged and j == rr.hedge_replica:
                        self._trace.event(
                            rr.trace, "hedge_won", now, replica=j
                        )
                self._hedge.disarm(rr)
                self._hedge_release(rr)
                self._awaiting[j].pop(rr, None)
                if (
                    self.policy == "two_tier"
                    and j in self._prefill_set
                    and not leg.finished
                    and self._begin_migration(rr, j, leg, now)
                ):
                    continue  # in the migration book, not streaming
                self._streaming[j][rr] = None

    # -- two-tier migration (the disaggregation placement brain) --------

    def _begin_migration(self, rr: RoutedRequest, i: int, leg,
                         now: float) -> bool:
        """First token just resolved on prefill replica ``i``: capture
        the stream's KV pages for the decode tier, unless the payload
        exceeds the migration-size threshold or no decode replica is
        routable — it then decodes where it prefilled (the graceful
        keep-local path, counted in ``n_kept_local``)."""
        r = self.replicas[i]
        migrate_out = getattr(r, "migrate_out", None)
        if migrate_out is None or not any(
            j in self._decode_set for j in self._routable
        ):
            self.n_kept_local += 1
            return False
        thr = self.migrate_threshold_bytes
        if thr is not None:
            size = getattr(r, "migration_nbytes", None)
            if size is not None and size(leg) > thr:
                self.n_kept_local += 1
                return False
        ticket = migrate_out(leg)
        if self._trace is not None and rr.trace is not None:
            # the trace id rides INSIDE the ticket so an adopting
            # replica (possibly a different process in the live plane)
            # can keep stamping the same record
            try:
                ticket.trace = rr.trace
            except AttributeError:
                pass
            self._trace.event(
                rr.trace, "migrate_out", now, replica=i,
                nbytes=int(getattr(ticket, "nbytes", 0)),
                pages=int(getattr(ticket, "pages", 0) or 0),
            )
        delay = (
            ticket.nbytes / (self.migrate_gbs * 1e9)
            if self.migrate_gbs else 0.0
        )
        self._migrating[rr] = [ticket, now + delay, now]
        return True

    def _pick_decode(self, rr: RoutedRequest,
                     cands: list[int]) -> int:
        """Adoption target: the decode replica already holding the
        longest resident prefix of this stream's prompt (the pages the
        adoption will SHARE instead of landing twice), load-bounded
        exactly like ``prefix_affinity``; ``least_loaded`` otherwise."""
        return self._bounded_affinity(rr.prompt, cands)

    def _bounce_candidates(self, ticket) -> list[int]:
        """Where a due-but-unadoptable migration may BOUNCE: empty
        while parking is justified — some routable decode replica
        could eventually adopt (``could_adopt``; a replica without the
        verb is assumed feasible, the sim twin's unbounded queue) —
        otherwise every routable replica that can adopt right now
        (the prefill tier included: zero drops beats tier purity)."""
        for j in self._routable:
            if j not in self._decode_set:
                continue
            ce = getattr(self.replicas[j], "could_adopt", None)
            if ce is None or ce(ticket):
                return []
        cands = []
        for j in self._routable:
            ca = getattr(self.replicas[j], "can_adopt", None)
            if ca is None or ca(ticket):
                cands.append(j)
        return cands

    def _land_migrations(self, now: float) -> None:
        """Land every due migration whose decode tier can adopt it
        right now; the rest stay booked and retry next step (capacity
        frees as decode-tier requests retire — their ticks are the
        events the sim driver advances to). Parking is only legal
        while some routable decode replica could EVER adopt the
        ticket (``could_adopt``): a dead decode tier, or one whose
        every replica is config-incompatible with the stream, BOUNCES
        it back onto any adoptable replica — zero drops, the
        ``_evacuate`` contract extended to the mid-migration window."""
        for rr in list(self._migrating):
            ticket, ready, t0 = self._migrating[rr]
            if ready > now + 1e-12:
                continue
            bounced = False
            cands = []
            for j in self._routable:
                if j not in self._decode_set:
                    continue
                ca = getattr(self.replicas[j], "can_adopt", None)
                if ca is None or ca(ticket):
                    cands.append(j)
            if not cands:
                cands = self._bounce_candidates(ticket)
                if not cands:
                    continue  # parked (or nowhere at all yet)
                bounced = True
            j = self._pick_decode(rr, cands)
            leg = self.replicas[j].adopt(ticket)
            del self._migrating[rr]
            rr._legs = [(j, leg)]
            rr.replica = j
            rr.migrated = True
            self._streaming[j][rr] = None
            self.n_migrated += 1
            if bounced:
                self.n_bounced += 1
            self.migrated_bytes += int(getattr(ticket, "nbytes", 0))
            if self._obs is not None:
                self._obs.migrated(rr, ticket, j, now, now - t0)
            if self._trace is not None and rr.trace is not None:
                self._trace.event(
                    rr.trace, "adopt", now, replica=j,
                    bounced=bounced,
                )

    def _resolve_completions(
        self, now: float, ticked: Sequence[int]
    ) -> list[RoutedRequest]:
        done: list[RoutedRequest] = []
        for j in ticked:
            if not self._streaming[j]:
                continue
            for rr in list(self._streaming[j]):
                leg = rr._legs[0][1]
                if not leg.finished:
                    continue
                del self._streaming[j][rr]
                rr.finished = True
                rr.t_done = now
                if rr.rerouted:
                    rr.outcome = "rerouted"
                elif rr.hedged:
                    rr.outcome = (
                        "hedge_won" if j == rr.hedge_replica else
                        "hedged"
                    )
                elif rr.migrated:
                    rr.outcome = "migrated"
                else:
                    rr.outcome = "ok"
                self.n_completed += 1
                if self._obs is not None:
                    self._obs.completed(rr)
                if self._trace is not None and rr.trace is not None:
                    self._trace.event(
                        rr.trace, "retired", now, outcome=rr.outcome,
                        tokens=len(leg.tokens),
                    )
                done.append(rr)
        return done

    def step(self) -> list[RoutedRequest]:
        """One fleet tick; returns the requests completed in it."""
        self._probe_health()
        if self._orphans and self.routable_replicas:
            now = self._now()
            orphans, self._orphans = self._orphans, {}
            for rr in orphans:
                rr.rerouted -= 1  # _reroute recounts
                self.n_rerouted -= 1
                self._reroute(rr, now)
        now = self._now()
        ticked: list[int] = []
        for i in self._routable:
            r = self.replicas[i]
            nt = getattr(r, "next_tick_at", _NO_SCHEDULE)
            if nt is _NO_SCHEDULE:
                # live replica (no tick schedule): step whenever busy
                if r.pending or r.active:
                    r.step()
                    ticked.append(i)
            elif nt is not None and nt <= now + 1e-12:
                r.step()
                ticked.append(i)
        # partitioned replicas KEEP TICKING (partition != death): their
        # in-flight work progresses and burns capacity, but they are
        # never in `ticked` — their first tokens and completions are
        # unreachable until heal() reconciles. Guarded: step() is the
        # hottest loop in a million-event day and partitions are rare,
        # so the common case pays one falsy check, not a sort.
        if self._partitioned:
            for i in sorted(self._partitioned):
                r = self.replicas[i]
                nt = getattr(r, "next_tick_at", _NO_SCHEDULE)
                if nt is _NO_SCHEDULE:
                    if r.pending or r.active:
                        r.step()
                elif nt is not None and nt <= now + 1e-12:
                    r.step()
        if self.clock is None:
            now = self._now()  # live: replica ticks took real time
        if ticked:
            self._resolve_first_tokens(now, ticked)
            done = self._resolve_completions(now, ticked)
        else:
            done = []
        if self._migrating:
            self._land_migrations(now)
        self._fire_hedges(now)
        if self._obs is not None:
            self._obs.depths(self)
        return done

    def next_event_at(self) -> float | None:
        """The earliest virtual time anything router-visible happens: a
        busy routable replica's next tick (replicas exposing
        ``next_tick_at`` — the sim protocol) or a pending hedge
        deadline. None when idle; the virtual-time driver
        (:func:`~..sim.workload.run_router_day`) advances the clock
        here between steps. Live replicas carry no tick schedule — a
        wall-clock serving loop just calls :meth:`step` hot."""
        best = None
        reps = self.replicas
        for i in self._routable:
            t = getattr(reps[i], "next_tick_at", None)
            if t is not None and (best is None or t < best):
                best = t
        # a partitioned replica's ticks are events too: it keeps
        # working through the partition, and the virtual-time driver
        # must advance to its ticks or its in-flight work would freeze
        # (that would be death, which a partition is not)
        for i in self._partitioned:
            t = getattr(reps[i], "next_tick_at", None)
            if t is not None and (best is None or t < best):
                best = t
        if self._hedge:
            d = self._hedge.next_deadline()
            if d is not None and (best is None or d < best):
                best = d
        if self._migrating:
            # still-transferring migrations are events; a DUE one
            # parked on decode-tier capacity is not (its wake signal
            # is the tier's next tick — capacity frees at retirement,
            # and a past-due time here would spin the driver). A due
            # one the next step would BOUNCE (decode tier dead or
            # statically unfit, an adoptable replica elsewhere) IS an
            # event — without it a day whose decode tier died with a
            # parked ticket reads as stalled before the rescuing step
            # ever runs.
            now = self._now()
            for ticket, ready, t0 in self._migrating.values():
                if ready > now:
                    if best is None or ready < best:
                        best = ready
                elif self._bounce_candidates(ticket):
                    if best is None or now < best:
                        best = now
        return best

    def drain(self, *, max_steps: int = 1_000_000) -> None:
        """Step until every in-flight request completes (live loops;
        the sim driver uses :meth:`next_event_at` instead)."""
        for _ in range(max_steps):
            if self.in_flight == 0:
                return
            self.step()
        raise RuntimeError(
            f"not drained after {max_steps} steps: "
            f"{self.in_flight} requests in flight"
        )

    def __repr__(self) -> str:
        return (
            f"RequestRouter({self.policy}, "
            f"{len(self.routable_replicas)}/{len(self.replicas)} "
            f"routable, {self.in_flight} in flight)"
        )
